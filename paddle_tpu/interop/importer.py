"""One-way importer for reference-format model artifacts.

Reads the reference ecosystem's saved inference models — the `__model__`
ProgramDesc protobuf (paddle/fluid/framework/framework.proto:50-240) plus
persistable tensors serialized by SerializeToStream
(paddle/fluid/framework/lod_tensor.cc:190-215, tensor_util.cc TensorToStream)
— and executes them with this framework's jax kernels. The reference's
load path is python/paddle/fluid/io.py load_inference_model.

TPU-native framing: the imported op list is executed through jnp ops (an
interpreter over block 0), so a whole imported model can also be wrapped in
one jax.jit via `PaddleProgram.as_fn()` — XLA then fuses it exactly like a
natively-built program.
"""
from __future__ import annotations

import math
import os
import struct
from typing import Dict, List, Optional

import numpy as np

from . import wire
from .wire import decode_fields, get1, get_all, get_repeated_varints

__all__ = ["PaddleProgram", "load_paddle_inference_model",
           "parse_program_desc", "read_lod_tensor_stream"]

# VarType.Type enum (framework.proto:117-155) -> numpy dtype
DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
          4: np.float16, 5: np.float32, 6: np.float64, 20: np.uint8,
          21: np.int8}

# AttrType enum (framework.proto:25-39)
(A_INT, A_FLOAT, A_STRING, A_INTS, A_FLOATS, A_STRINGS, A_BOOL, A_BOOLS,
 A_BLOCK, A_LONG, A_BLOCKS, A_LONGS, A_FLOAT64S) = range(13)


def _parse_attr(buf):
    """-> (name, python value, AttrType) — the type rides along so programs
    re-serialize losslessly (serializer.py)."""
    f = decode_fields(buf)
    name = get1(f, 1).decode()
    atype = get1(f, 2)
    if atype == A_INT:
        # negative int32 attrs ride the wire as 64-bit two's-complement
        # varints (proto2 int32 semantics)
        val = wire.to_signed(get1(f, 3, 0), 64)
    elif atype == A_FLOAT:
        val = wire.f32(get1(f, 4, 0))
    elif atype == A_STRING:
        val = get1(f, 5, b"").decode()
    elif atype == A_INTS:
        val = get_repeated_varints(f, 6)
    elif atype == A_FLOATS:
        val = [wire.f32(v) for v in wire.get_all(f, 7)]
    elif atype == A_STRINGS:
        val = [v.decode() for v in get_all(f, 8)]
    elif atype == A_BOOL:
        val = bool(get1(f, 10, 0))
    elif atype == A_BOOLS:
        val = [bool(v) for v in get_repeated_varints(f, 11, signed=False)]
    elif atype == A_BLOCK:
        val = get1(f, 12, 0)
    elif atype == A_LONG:
        val = wire.to_signed(get1(f, 13, 0))
    elif atype == A_BLOCKS:
        val = get_repeated_varints(f, 14)
    elif atype == A_LONGS:
        val = get_repeated_varints(f, 15)
    elif atype == A_FLOAT64S:
        val = [wire.f64(v) for v in get_all(f, 16)]
    else:
        val = None
    return name, val, atype


class OpDesc:
    def __init__(self, buf):
        f = decode_fields(buf)
        self.type = get1(f, 3).decode()
        self.inputs: Dict[str, List[str]] = {}
        self.outputs: Dict[str, List[str]] = {}
        for v in get_all(f, 1):
            vf = decode_fields(v)
            self.inputs[get1(vf, 1).decode()] = [a.decode()
                                                 for a in get_all(vf, 2)]
        for v in get_all(f, 2):
            vf = decode_fields(v)
            self.outputs[get1(vf, 1).decode()] = [a.decode()
                                                  for a in get_all(vf, 2)]
        self.attrs = {}
        self.attr_types = {}
        for b in get_all(f, 4):
            name_, val_, atype_ = _parse_attr(b)
            self.attrs[name_] = val_
            self.attr_types[name_] = atype_

    def in1(self, name, default=None):
        args = self.inputs.get(name) or []
        return args[0] if args else default

    def out1(self, name, default=None):
        args = self.outputs.get(name) or []
        return args[0] if args else default


class VarDesc:
    def __init__(self, buf):
        f = decode_fields(buf)
        self.name = get1(f, 1).decode()
        self.persistable = bool(get1(f, 3, 0))
        self.dtype = None
        self.shape = None
        self.dtype_enum = None
        tf = decode_fields(get1(f, 2, b""))
        self.type_id = get1(tf, 1)
        lod = get1(tf, 3)
        if lod is not None:
            tdesc = decode_fields(get1(decode_fields(lod), 1, b""))
            self.dtype_enum = get1(tdesc, 1)
            self.dtype = DTYPES.get(self.dtype_enum)
            self.shape = get_repeated_varints(tdesc, 2)


class BlockDesc:
    def __init__(self, buf):
        f = decode_fields(buf)
        self.idx = get1(f, 1, 0)
        # proto int32 rides the wire as a 64-bit sign-extended varint, so
        # the sign bit lives at bit 63, not 31 (a 32-bit interpretation
        # turns -1 into 2^64-2^32-1)
        self.parent_idx = wire.to_signed(get1(f, 2, 0), 64)
        self.vars = {v.name: v for v in
                     (VarDesc(b) for b in get_all(f, 3))}
        self.ops = [OpDesc(b) for b in get_all(f, 4)]


def parse_program_desc(buf: bytes) -> List[BlockDesc]:
    blocks = [BlockDesc(b) for b in get_all(decode_fields(buf), 1)]
    # sub_block attrs index by BlockDesc.idx; the repeated field's wire
    # order is not guaranteed to match, so order by idx
    blocks.sort(key=lambda b: b.idx)
    for i, b in enumerate(blocks):
        if b.idx != i:
            raise ValueError(f"ProgramDesc block indices not contiguous: "
                             f"{[x.idx for x in blocks]}")
    return blocks


def read_lod_tensor_stream(f) -> Optional[np.ndarray]:
    """One SerializeToStream record (lod_tensor.cc:190): u32 version, u64
    lod_level + levels, then TensorToStream: u32 version, i32 desc size,
    TensorDesc proto, raw data. Returns None at EOF."""
    head = f.read(4)
    if len(head) < 4:
        return None
    (version,) = struct.unpack("<I", head)
    if version != 0:
        raise ValueError(f"unsupported LoDTensor version {version}")
    (lod_level,) = struct.unpack("<Q", f.read(8))
    for _ in range(lod_level):
        (nbytes,) = struct.unpack("<Q", f.read(8))
        f.read(nbytes)
    (tversion,) = struct.unpack("<I", f.read(4))
    if tversion != 0:
        raise ValueError(f"unsupported Tensor version {tversion}")
    (dsize,) = struct.unpack("<i", f.read(4))
    desc = decode_fields(f.read(dsize))
    dtype = DTYPES[get1(desc, 1)]
    dims = get_repeated_varints(desc, 2)
    n = int(np.prod(dims)) if dims else 1
    data = np.frombuffer(f.read(n * np.dtype(dtype).itemsize), dtype=dtype)
    return data.reshape(dims).copy()


# ---------------------------------------------------------------------------
# op interpreter
# ---------------------------------------------------------------------------

def _bcast_y(x, y, axis):
    """elementwise_* broadcasting: align y's dims at `axis` of x
    (elementwise_op_function.h GetMidDims)."""
    if y.ndim == x.ndim:
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    shape[axis:axis + y.ndim] = y.shape
    return y.reshape(shape)


def _interp_axis(in_size, out_size, align_corners, align_mode):
    """Source coordinates for one axis, matching the reference
    interpolate kernels (operators/interpolate_op.h):
      align_corners        src = dst * (in-1)/(out-1)
      align_mode=1 default src = dst * in/out          (origin-aligned)
      align_mode=0         src = (dst+0.5) * in/out - 0.5  (half-pixel)
    Returns (lo, hi, frac) as static numpy (attrs fix the shapes)."""
    dst = np.arange(out_size, dtype=np.float64)
    if align_corners:
        ratio = (in_size - 1) / max(out_size - 1, 1)
        src = dst * ratio
    elif align_mode == 1:
        src = dst * (in_size / out_size)
    else:
        src = (dst + 0.5) * (in_size / out_size) - 0.5
    src = np.clip(src, 0.0, in_size - 1)
    lo = np.floor(src).astype(np.int32)
    hi = np.minimum(lo + 1, in_size - 1)
    return lo, hi, (src - lo).astype(np.float32)


def _interp_2d(jnp, x, oh, ow, *, bilinear, align_corners, align_mode):
    """NCHW resize by static gathers — exact reference sampling semantics
    in every mode (incl. the fluid DEFAULT align_mode=1 origin-aligned
    bilinear and floor-indexed nearest, neither of which
    jax.image.resize reproduces)."""
    ih, iw = x.shape[2], x.shape[3]
    if not bilinear:
        # nearest: align_corners rounds on the (in-1)/(out-1) grid,
        # otherwise floor(dst * in/out) (interpolate_op.h NearestNeighbor)
        if align_corners:
            # the reference rounds half UP (static_cast<int>(ratio*j + .5),
            # interpolate_op.h) — np.rint's half-to-even differs at exact
            # .5 coordinates
            idx_h = (np.arange(oh) * (ih - 1) / max(oh - 1, 1)
                     + 0.5).astype(np.int32)
            idx_w = (np.arange(ow) * (iw - 1) / max(ow - 1, 1)
                     + 0.5).astype(np.int32)
        else:
            idx_h = np.minimum((np.arange(oh) * ih // oh), ih - 1)
            idx_w = np.minimum((np.arange(ow) * iw // ow), iw - 1)
        return jnp.take(jnp.take(x, idx_h, axis=2), idx_w, axis=3)
    lo_h, hi_h, wh = _interp_axis(ih, oh, align_corners, align_mode)
    lo_w, hi_w, ww = _interp_axis(iw, ow, align_corners, align_mode)
    wh = wh[None, None, :, None]
    ww = ww[None, None, None, :]
    row = (jnp.take(x, lo_h, axis=2) * (1.0 - wh)
           + jnp.take(x, hi_h, axis=2) * wh)
    out = (jnp.take(row, lo_w, axis=3) * (1.0 - ww)
           + jnp.take(row, hi_w, axis=3) * ww)
    # the f32 weights promote bf16/f16 inputs: blend in f32, return the
    # input dtype (what the reference kernel and jax.image.resize do)
    return out.astype(x.dtype)


def dropout_infer_scale(attrs) -> float:
    """Inference-time output scale of a fluid dropout op. The fluid-era
    default dropout_implementation 'downgrade_in_infer' scales inference
    output by (1 - dropout_prob) (reference python/paddle/fluid/layers/
    nn.py:1056, delete_dropout_op_pass); only 'upscale_in_train' (or
    p == 0) is an identity. Shared by the eager interpreter and the
    identity_elimination inference pass so the two can't drift."""
    p = float(attrs.get("dropout_prob", 0.5))
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    return 1.0 if impl == "upscale_in_train" or p == 0.0 else 1.0 - p


# var types that never hold tensor values (scope machinery): excluded
# from traced carries / persistable sync
_SCOPE_TYPE_IDS = {11, 12, 14, 17}  # STEP_SCOPES/LOD_RANK_TABLE/PLACE_LIST/RAW


def _is_scope_var(name, blocks):
    for b in blocks or ():
        v = b.vars.get(name)
        if v is not None:
            return v.type_id in _SCOPE_TYPE_IDS
    return False


def _sub_block_writes(sub, blocks=None):
    """Tensor names a block's ops assign (flat-env: these update the
    enclosing scope). Recurses into nested while/conditional_block sub
    blocks (their writes escape too) and drops scope-typed outputs
    (StepScopes etc.), which never hold tensor values."""
    names = set()
    for o in sub.ops:
        for args in o.outputs.values():
            names.update(args)
        if blocks is not None and o.type in ("while", "conditional_block"):
            nested = o.attrs.get("sub_block")
            if nested is not None:
                names.update(_sub_block_writes(blocks[nested], blocks))
    return sorted(n for n in names if not _is_scope_var(n, blocks))


def _out_req(op, key):
    """Required-output name: a missing ParamOut/Moment*Out would make the
    update a silent no-op, so refuse loudly instead."""
    n = op.out1(key)
    if n is None:
        raise ValueError(
            f"imported '{op.type}' op lacks required output {key!r}")
    return n


def _run_op(op, V, jnp, blocks=None, traced=False):
    """Execute one OpDesc against var store V. Covers the inference op core;
    unmapped types raise with the op name. `blocks` enables the control-flow
    ops (while/conditional_block), which interpret their sub-block eagerly —
    under jax tracing their data-dependent python conditions cannot run; use
    PaddleProgram.run() (eager) for programs containing them."""
    t = op.type
    a = op.attrs
    if t == "feed":
        return  # handled by run()
    if t == "fetch":
        return
    if t == "while":
        # operators/controlflow/while_op.cc: run sub_block while the
        # Condition var holds; the block updates the enclosing scope's
        # names in place (flat-env semantics)
        if blocks is None:
            raise NotImplementedError(
                "imported 'while' op needs its program's blocks "
                "(PaddleProgram.run or as_fn)")
        cond = op.in1("Condition")
        sub = blocks[a["sub_block"]]
        if traced:
            # under jit the loop lowers to lax.while_loop: the carry is
            # every name the sub-block writes (+ the condition var); all
            # must be defined before the loop with loop-invariant
            # shape/dtype (true of reference-authored programs, which
            # init loop state with fill_constant)
            import jax

            carry_names = sorted(set(_sub_block_writes(sub, blocks)) | {cond})
            missing = [n for n in carry_names if n not in V]
            if missing:
                raise NotImplementedError(
                    f"imported 'while' writes {missing} which have no "
                    f"value before the loop — cannot form a "
                    f"lax.while_loop carry")

            def cond_fn(c):
                return jnp.reshape(c[cond], ()).astype(bool)

            def body_fn(c):
                v2 = dict(V)
                v2.update(c)
                for sop in sub.ops:
                    _run_op(sop, v2, jnp, blocks, traced=True)
                return {n: v2[n] for n in carry_names}

            init = {n: jnp.asarray(V[n]) for n in carry_names}
            V.update(jax.lax.while_loop(cond_fn, body_fn, init))
            return
        guard = 0
        while bool(np.asarray(V[cond]).reshape(())):
            for sop in sub.ops:
                _run_op(sop, V, jnp, blocks)
            guard += 1
            if guard > 100000:
                raise RuntimeError("imported while op exceeded 100k "
                                   "iterations (non-terminating?)")
        return
    if t == "conditional_block":
        if blocks is None:
            raise NotImplementedError(
                "imported 'conditional_block' op needs its program's "
                "blocks (PaddleProgram.run or as_fn)")
        conds = op.inputs.get("Cond") or op.inputs.get("Condition") or []
        if not conds:
            raise ValueError(
                "imported 'conditional_block' op has no Cond input — "
                "refusing to run the guarded block unconditionally")
        if traced and a.get("is_scalar_condition", False):
            # under jit the branch lowers to lax.cond; the false branch
            # passes through the pre-existing values of the names the
            # sub-block writes (the reference pattern assigns defaults
            # before the conditional)
            import jax

            sub = blocks[a["sub_block"]]
            writes = _sub_block_writes(sub, blocks)
            missing = [n for n in writes if n not in V]
            if missing:
                raise NotImplementedError(
                    f"imported 'conditional_block' writes {missing} with "
                    f"no default value — lax.cond needs both branches to "
                    f"produce them")
            pred = jnp.reshape(V[conds[0]], ()).astype(bool)
            for c in conds[1:]:
                pred = pred & jnp.reshape(V[c], ()).astype(bool)

            def true_fn(c):
                v2 = dict(V)
                v2.update(c)
                for sop in sub.ops:
                    _run_op(sop, v2, jnp, blocks, traced=True)
                return {n: jnp.asarray(v2[n]) for n in writes}

            init = {n: jnp.asarray(V[n]) for n in writes}
            V.update(jax.lax.cond(pred, true_fn, lambda c: c, init))
            return
        if traced:
            # non-scalar mode: fires iff the Cond inputs are non-empty —
            # a SHAPE property, static at trace time
            if all(c in V and np.prod(jnp.shape(V[c])) > 0 for c in conds):
                for sop in blocks[a["sub_block"]].ops:
                    _run_op(sop, V, jnp, blocks, traced=True)
            return
        if a.get("is_scalar_condition", False):
            # scalar mode: fire on the boolean value of the scalar cond
            fire = True
            for c in conds:
                if c not in V:
                    raise ValueError(
                        f"imported 'conditional_block' scalar Cond {c!r} "
                        f"is not initialized")
                arr = np.asarray(V[c])
                if arr.size != 1:
                    raise ValueError(
                        f"imported 'conditional_block' scalar Cond {c!r} "
                        f"has size {arr.size}, expected a scalar")
                fire = fire and bool(arr.reshape(()))
        else:
            # non-scalar mode (the proto default): the sub-block runs iff
            # the Cond inputs are initialized and NON-EMPTY — element
            # values are irrelevant, and an empty Cond means skip
            # (conditional_block_op.h:124-128)
            fire = all(c in V and np.asarray(V[c]).size > 0 for c in conds)
        if fire:
            for sop in blocks[a["sub_block"]].ops:
                _run_op(sop, V, jnp, blocks)
        return
    if t in ("mul",):
        x, y = V[op.in1("X")], V[op.in1("Y")]
        xn = a.get("x_num_col_dims", 1)
        yn = a.get("y_num_col_dims", 1)
        # leading dims may be SYMBOLIC (shape-polymorphic export of an
        # imported program) — never int()-coerce them; -1 folds the lead
        x2 = x.reshape(-1, math.prod(x.shape[xn:]))
        y2 = y.reshape(math.prod(y.shape[:yn]), -1)
        out = x2 @ y2
        V[op.out1("Out")] = out.reshape(*x.shape[:xn], *y.shape[yn:])
    elif t in ("matmul", "matmul_v2"):
        x, y = V[op.in1("X")], V[op.in1("Y")]
        tx = a.get("transpose_X", a.get("trans_x", False))
        ty = a.get("transpose_Y", a.get("trans_y", False))
        if tx:
            x = jnp.swapaxes(x, -1, -2)
        if ty:
            y = jnp.swapaxes(y, -1, -2)
        V[op.out1("Out")] = (x @ y) * a.get("alpha", 1.0)
    elif t.startswith("elementwise_") and not t.endswith("_grad"):
        x, y = V[op.in1("X")], V[op.in1("Y")]
        y = _bcast_y(x, y, a.get("axis", -1))
        fn = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
              "div": jnp.divide, "max": jnp.maximum, "min": jnp.minimum,
              "pow": jnp.power, "floordiv": jnp.floor_divide,
              "mod": jnp.mod}.get(t.split("_", 1)[1])
        if fn is None:
            raise NotImplementedError(
                f"imported op '{t}' has no TPU-native mapping yet")
        V[op.out1("Out")] = fn(x, y)
    elif t in ("relu", "sigmoid", "tanh", "exp", "sqrt", "abs", "floor",
               "ceil", "log", "relu6", "silu", "swish", "softplus",
               "mish", "rsqrt", "square"):
        import jax

        fn = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid,
              "tanh": jnp.tanh, "exp": jnp.exp, "sqrt": jnp.sqrt,
              "abs": jnp.abs, "floor": jnp.floor, "ceil": jnp.ceil,
              "log": jnp.log, "relu6": jax.nn.relu6, "silu": jax.nn.silu,
              "swish": jax.nn.silu, "softplus": jax.nn.softplus,
              "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
              "rsqrt": jax.lax.rsqrt, "square": jnp.square}[t]
        V[op.out1("Out")] = fn(V[op.in1("X")])
    elif t == "leaky_relu":
        import jax

        V[op.out1("Out")] = jax.nn.leaky_relu(
            V[op.in1("X")], negative_slope=a.get("alpha", 0.02))
    elif t == "hard_sigmoid":
        x = V[op.in1("X")]
        s, off = a.get("slope", 0.2), a.get("offset", 0.5)
        V[op.out1("Out")] = jnp.clip(x * s + off, 0.0, 1.0)
    elif t == "hard_swish":
        x = V[op.in1("X")]
        th = a.get("threshold", 6.0)
        V[op.out1("Out")] = (x * jnp.clip(x + a.get("offset", 3.0), 0.0, th)
                             / a.get("scale", 6.0))
    elif t == "clip":
        V[op.out1("Out")] = jnp.clip(V[op.in1("X")], a.get("min"),
                                     a.get("max"))
    elif t == "pow":
        if op.in1("FactorTensor"):
            raise NotImplementedError(
                "imported op 'pow' with a FactorTensor input has no "
                "mapping yet (attr-factor only)")
        V[op.out1("Out")] = jnp.power(V[op.in1("X")],
                                      a.get("factor", 1.0))
    elif t == "stack":
        V[op.out1("Y", op.out1("Out"))] = jnp.stack(
            [V[n] for n in op.inputs["X"]], axis=a.get("axis", 0))
    elif t == "unstack":
        parts = jnp.split(V[op.in1("X")],
                          V[op.in1("X")].shape[a.get("axis", 0)],
                          axis=a.get("axis", 0))
        for name, p in zip(op.outputs["Y"], parts):
            V[name] = jnp.squeeze(p, axis=a.get("axis", 0))
    elif t == "gather":
        V[op.out1("Out")] = jnp.take(V[op.in1("X")],
                                     V[op.in1("Index")].reshape(-1),
                                     axis=a.get("axis", 0))
    elif t in ("arg_max", "arg_min"):
        fn = jnp.argmax if t == "arg_max" else jnp.argmin
        axis = a.get("axis", -1)
        out = fn(V[op.in1("X")], axis=axis)
        if a.get("keepdims", a.get("keep_dims", False)):
            out = jnp.expand_dims(out, axis)
        V[op.out1("Out")] = out.astype(DTYPES.get(a.get("dtype", 3),
                                                  np.int64))
    elif t in ("top_k", "top_k_v2"):
        import jax

        x = V[op.in1("X")]
        axis = a.get("axis", -1)
        if op.in1("K"):
            # K arrives as a 1-element tensor; its value is concrete under
            # eager interpretation (the reference reads it the same way:
            # top_k_op.cc k from the K input at run time)
            try:
                k = int(np.asarray(V[op.in1("K")]).reshape(()))
            except jax.errors.TracerArrayConversionError:
                raise NotImplementedError(
                    f"imported op '{t}' with a tensor K input needs a "
                    f"concrete value (eager PaddleProgram.run); under jit "
                    f"the output shape would be data-dependent")
        else:
            k = a.get("k", 1)
        moved = axis not in (-1, x.ndim - 1)
        xx = jnp.moveaxis(x, axis, -1) if moved else x
        if not a.get("largest", True):
            xx = -xx
        vals, idx = jax.lax.top_k(xx, k)
        if not a.get("largest", True):
            vals = -vals
        if moved:
            vals = jnp.moveaxis(vals, -1, axis)
            idx = jnp.moveaxis(idx, -1, axis)
        V[op.out1("Out")] = vals
        V[op.out1("Indices")] = idx.astype(np.int64)
    elif t == "mean":
        V[op.out1("Out")] = jnp.mean(V[op.in1("X")])
    elif t == "reduce_prod":
        x = V[op.in1("X")]
        dims = a.get("dim") or list(range(x.ndim))
        V[op.out1("Out")] = jnp.prod(x, axis=tuple(dims),
                                     keepdims=a.get("keep_dim", False))
    elif t in ("expand_v2", "tile"):
        x = V[op.in1("X")]
        reps = a.get("shape") or a.get("repeat_times")
        if t == "expand_v2":
            # -1 keeps the input dim; input dims RIGHT-align against the
            # target shape (numpy broadcast orientation)
            off = len(reps) - x.ndim
            tgt = [x.shape[i - off] if (d == -1 and i >= off) else d
                   for i, d in enumerate(reps)]
            V[op.out1("Out")] = jnp.broadcast_to(x, tgt)
        else:
            V[op.out1("Out")] = jnp.tile(x, reps)
    elif t in ("nearest_interp", "nearest_interp_v2", "bilinear_interp",
               "bilinear_interp_v2"):
        import jax

        x = V[op.in1("X")]
        if op.in1("OutSize") or op.inputs.get("SizeTensor") \
                or op.in1("Scale"):
            # tensor-shaped target size: concrete under eager
            # interpretation (like the reference reading OutSize at run
            # time); under jit the output shape would be data-dependent
            try:
                if op.in1("OutSize"):
                    hw = np.asarray(V[op.in1("OutSize")]).reshape(-1)
                    oh, ow = int(hw[0]), int(hw[1])
                elif op.inputs.get("SizeTensor"):
                    st = [int(np.asarray(V[n]).reshape(()))
                          for n in op.inputs["SizeTensor"]]
                    oh, ow = st[0], st[1]
                else:
                    sc = np.asarray(V[op.in1("Scale")]).reshape(-1)
                    sh = float(sc[0])
                    sw = float(sc[1] if sc.size > 1 else sc[0])
                    oh = int(x.shape[2] * sh)
                    ow = int(x.shape[3] * sw)
            except jax.errors.TracerArrayConversionError:
                raise NotImplementedError(
                    f"imported op '{t}' takes its target size from a "
                    f"tensor input, which needs a concrete value (eager "
                    f"PaddleProgram.run, not jit)")
        else:
            oh = a.get("out_h", 0)
            ow = a.get("out_w", 0)
        if oh <= 0 or ow <= 0:
            scale = a.get("scale")
            if isinstance(scale, (list, tuple)) and scale:
                sh = scale[0]
                sw = scale[1] if len(scale) > 1 else scale[0]
            else:
                sh = sw = scale or 0.0
            if sh <= 0 or sw <= 0:
                raise NotImplementedError(
                    f"imported op '{t}' specifies neither out_h/out_w nor "
                    f"a positive scale attr")
            oh, ow = int(x.shape[2] * sh), int(x.shape[3] * sw)
        V[op.out1("Out")] = _interp_2d(
            jnp, x, oh, ow, bilinear=t.startswith("bilinear"),
            align_corners=bool(a.get("align_corners", False)),
            align_mode=int(a.get("align_mode", 1)))
    elif t == "fill_constant_batch_size_like":
        ref = V[op.in1("Input")]
        shape = list(a["shape"])
        shape[a.get("output_dim_idx", 0)] = ref.shape[
            a.get("input_dim_idx", 0)]
        V[op.out1("Out")] = jnp.full(shape, a.get("value", 0.0),
                                     DTYPES[a.get("dtype", 5)])
    elif t == "gelu":
        import jax

        V[op.out1("Out")] = jax.nn.gelu(V[op.in1("X")],
                                        approximate=a.get("approximate",
                                                          False))
    elif t == "softmax":
        import jax

        V[op.out1("Out")] = jax.nn.softmax(V[op.in1("X")],
                                           axis=a.get("axis", -1))
    elif t == "scale":
        x = V[op.in1("X")]
        s, b = a.get("scale", 1.0), a.get("bias", 0.0)
        if a.get("bias_after_scale", True):
            V[op.out1("Out")] = x * s + b
        else:
            V[op.out1("Out")] = (x + b) * s
    elif t == "cast":
        V[op.out1("Out")] = V[op.in1("X")].astype(DTYPES[a["out_dtype"]])
    elif t in ("reshape", "reshape2"):
        x = V[op.in1("X")]
        # paddle reshape semantics: 0 copies the corresponding input dim
        shape = [x.shape[i] if d == 0 else d
                 for i, d in enumerate(a["shape"])]
        V[op.out1("Out")] = x.reshape(shape)
    elif t in ("transpose", "transpose2"):
        V[op.out1("Out")] = jnp.transpose(V[op.in1("X")], a["axis"])
    elif t in ("flatten", "flatten2", "flatten_contiguous_range"):
        x = V[op.in1("X")]
        start = a.get("start_axis", a.get("axis", 1))
        stop = a.get("stop_axis", x.ndim - 1)
        shape = (list(x.shape[:start])
                 + [int(np.prod(x.shape[start:stop + 1]))]
                 + list(x.shape[stop + 1:]))
        V[op.out1("Out")] = x.reshape(shape)
    elif t in ("squeeze", "squeeze2"):
        x = V[op.in1("X")]
        axes = a.get("axes") or [i for i, d in enumerate(x.shape) if d == 1]
        V[op.out1("Out")] = jnp.squeeze(x, axis=tuple(axes))
    elif t in ("unsqueeze", "unsqueeze2"):
        V[op.out1("Out")] = jnp.expand_dims(V[op.in1("X")],
                                            tuple(a["axes"]))
    elif t == "concat":
        V[op.out1("Out")] = jnp.concatenate(
            [V[n] for n in op.inputs["X"]], axis=a.get("axis", 0))
    elif t == "split":
        x = V[op.in1("X")]
        axis = a.get("axis", 0)
        secs = a.get("sections") or None
        if secs:
            idx = np.cumsum(secs)[:-1].tolist()
            parts = jnp.split(x, idx, axis=axis)
        else:
            parts = jnp.split(x, a["num"], axis=axis)
        for name, p in zip(op.outputs["Out"], parts):
            V[name] = p
    elif t in ("lookup_table", "lookup_table_v2"):
        ids = V[op.in1("Ids")]
        if t == "lookup_table" and ids.shape[-1] == 1:
            ids = ids[..., 0]
        V[op.out1("Out")] = jnp.take(V[op.in1("W")], ids, axis=0)
    elif t == "layer_norm":
        x = V[op.in1("X")].astype(np.float32)
        ax = a.get("begin_norm_axis", 1)
        red = tuple(range(ax, x.ndim))
        mu = x.mean(axis=red, keepdims=True)
        var = ((x - mu) ** 2).mean(axis=red, keepdims=True)
        out = (x - mu) / jnp.sqrt(var + a.get("epsilon", 1e-5))
        shape = x.shape[ax:]
        if op.in1("Scale"):
            out = out * V[op.in1("Scale")].reshape(shape)
        if op.in1("Bias"):
            out = out + V[op.in1("Bias")].reshape(shape)
        V[op.out1("Y")] = out
    elif t == "batch_norm":
        x = V[op.in1("X")]
        c = x.shape[1]
        shape = (1, c) + (1,) * (x.ndim - 2)
        mean = V[op.in1("Mean")].reshape(shape)
        var = V[op.in1("Variance")].reshape(shape)
        out = (x - mean) / jnp.sqrt(var + a.get("epsilon", 1e-5))
        out = out * V[op.in1("Scale")].reshape(shape) \
            + V[op.in1("Bias")].reshape(shape)
        V[op.out1("Y")] = out
    elif t == "dropout":
        s = dropout_infer_scale(a)
        x = V[op.in1("X")]
        V[op.out1("Out")] = x if s == 1.0 else x * s
    elif t in ("conv2d", "depthwise_conv2d"):
        import jax

        x, w = V[op.in1("Input")], V[op.in1("Filter")]
        pads = a.get("paddings", [0, 0])
        if len(pads) == 2:
            pads = [(pads[0], pads[0]), (pads[1], pads[1])]
        else:
            pads = [(pads[0], pads[1]), (pads[2], pads[3])]
        groups = a.get("groups", x.shape[1] if t == "depthwise_conv2d"
                       else 1)
        V[op.out1("Output")] = jax.lax.conv_general_dilated(
            x, w, window_strides=a.get("strides", [1, 1]), padding=pads,
            rhs_dilation=a.get("dilations", [1, 1]),
            feature_group_count=groups,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
    elif t == "pool2d":
        import jax

        x = V[op.in1("X")]
        if a.get("global_pooling", False):
            ksize = list(x.shape[2:])
            strides, pads = ksize, [0, 0]
        elif a.get("adaptive", False):
            # adaptive pooling with evenly-dividing output sizes (the
            # common CNN-head case): kernel = stride = in/out
            out_hw = a["ksize"]
            if any(x.shape[2 + i] % out_hw[i] for i in range(2)):
                raise NotImplementedError(
                    "adaptive pool2d with non-divisible output size")
            ksize = [x.shape[2 + i] // out_hw[i] for i in range(2)]
            strides, pads = ksize, [0, 0]
        else:
            ksize = a["ksize"]
            strides = a.get("strides", ksize)
            pads = a.get("paddings", [0, 0])
        dims = (1, 1) + tuple(ksize)
        strd = (1, 1) + tuple(strides)
        spec = [(0, 0), (0, 0)] + [(p, p) for p in pads]
        if a.get("pooling_type", "max") == "max":
            out = jax.lax.reduce_window(x, -np.inf, jax.lax.max, dims, strd,
                                        spec)
        else:
            summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd,
                                           spec)
            if a.get("exclusive", True):
                # paddle default: border windows divide by the count of
                # VALID (unpadded) elements, not the full kernel size
                ones = jnp.ones_like(x)
                count = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                              strd, spec)
                out = summed / count
            else:
                out = summed / np.prod(ksize)
        V[op.out1("Out")] = out
    elif t in ("reduce_mean", "reduce_sum", "reduce_max", "reduce_min"):
        x = V[op.in1("X")]
        dims = a.get("dim") or list(range(x.ndim))
        keep = a.get("keep_dim", False)
        fn = {"reduce_mean": jnp.mean, "reduce_sum": jnp.sum,
              "reduce_max": jnp.max, "reduce_min": jnp.min}[t]
        V[op.out1("Out")] = fn(x, axis=tuple(dims), keepdims=keep)
    elif t == "fill_constant":
        V[op.out1("Out")] = jnp.full(a["shape"], a.get("value", 0.0),
                                     DTYPES[a.get("dtype", 5)])
    elif t == "assign":
        V[op.out1("Out")] = V[op.in1("X")]
    elif t in ("less_than", "less_equal", "greater_than", "greater_equal",
               "equal", "not_equal"):
        fn = {"less_than": jnp.less, "less_equal": jnp.less_equal,
              "greater_than": jnp.greater,
              "greater_equal": jnp.greater_equal,
              "equal": jnp.equal, "not_equal": jnp.not_equal}[t]
        x, y = V[op.in1("X")], V[op.in1("Y")]
        V[op.out1("Out")] = fn(x, _bcast_y(x, y, a.get("axis", -1)))
    elif t in ("logical_and", "logical_or", "logical_xor"):
        fn = {"logical_and": jnp.logical_and,
              "logical_or": jnp.logical_or,
              "logical_xor": jnp.logical_xor}[t]
        V[op.out1("Out")] = fn(V[op.in1("X")], V[op.in1("Y")])
    elif t == "logical_not":
        V[op.out1("Out")] = jnp.logical_not(V[op.in1("X")])
    elif t == "increment":
        x = V[op.in1("X")]
        V[op.out1("Out")] = x + jnp.asarray(a.get("step", 1.0)).astype(
            x.dtype)
    elif t == "select_input":
        if blocks is None:  # mask concretization needs the eager path
            raise NotImplementedError(
                "imported 'select_input' op needs eager interpretation "
                "(PaddleProgram.run), not as_fn/jit")
        mask = int(np.asarray(V[op.in1("Mask")]).reshape(()))
        V[op.out1("Out")] = V[op.inputs["X"][mask]]
    elif t == "shape":
        V[op.out1("Out")] = jnp.asarray(V[op.in1("Input")].shape, np.int32)
    elif t == "slice":
        x = V[op.in1("Input")]
        idx = [slice(None)] * x.ndim
        for ax, st, en in zip(a["axes"], a["starts"], a["ends"]):
            idx[ax] = slice(st, None if en >= 2 ** 31 - 1 else en)
        out = x[tuple(idx)]
        dec = a.get("decrease_axis") or []
        if dec:
            out = jnp.squeeze(out, axis=tuple(dec))
        V[op.out1("Out")] = out
    # ---- training-program tail: backward + optimizer ops ----
    # Reference io.py also loads TRAIN programs (append_backward's *_grad
    # ops + optimizer ops); this tail lets an exported reference train
    # program RESUME here (VERDICT r3 next #4b). Grad semantics follow
    # the reference op kernels (paddle/fluid/operators/*_grad kernels).
    elif t == "mean_grad":
        x = V[op.in1("X")]
        dout = jnp.reshape(V[op.in1("Out@GRAD")], ())
        V[_out_req(op, "X@GRAD")] = jnp.full(x.shape, dout / x.size, x.dtype)
    elif t == "square_grad":
        x = V[op.in1("X")]
        V[_out_req(op, "X@GRAD")] = 2.0 * x * V[op.in1("Out@GRAD")]
    elif t in ("relu_grad", "sigmoid_grad", "tanh_grad"):
        out = V[op.in1("Out")]
        dout = V[op.in1("Out@GRAD")]
        V[_out_req(op, "X@GRAD")] = {
            "relu_grad": lambda: dout * (out > 0),
            "sigmoid_grad": lambda: dout * out * (1.0 - out),
            "tanh_grad": lambda: dout * (1.0 - out * out),
        }[t]()
    elif t in ("elementwise_add_grad", "elementwise_sub_grad",
               "elementwise_mul_grad"):
        x, y = V[op.in1("X")], V[op.in1("Y")]
        dout = V[op.in1("Out@GRAD")]
        yb = _bcast_y(x, y, a.get("axis", -1))

        def reduce_to(g, shape):
            """Sum g (shape == x.shape) down to the axis-aligned `shape`
            (undo the broadcast; len(shape) == g.ndim by construction)."""
            keep = tuple(i for i, d in enumerate(shape)
                         if d == 1 and g.shape[i] != 1)
            if keep:
                g = jnp.sum(g, axis=keep, keepdims=True)
            return g.reshape(shape)

        if t == "elementwise_mul_grad":
            dx, dy_full = dout * yb, dout * x
        elif t == "elementwise_sub_grad":
            dx, dy_full = dout, -dout
        else:
            dx, dy_full = dout, dout
        if op.out1("X@GRAD"):
            V[op.out1("X@GRAD")] = dx
        if op.out1("Y@GRAD"):
            # dOut reduced over the dims Y was broadcast along, aligned at
            # `axis` (elementwise_op_function.h backward)
            axis = a.get("axis", -1)
            axis = x.ndim - y.ndim if axis == -1 else axis
            aligned = (1,) * axis + tuple(y.shape) \
                + (1,) * (x.ndim - axis - y.ndim)
            V[op.out1("Y@GRAD")] = reduce_to(dy_full, aligned).reshape(
                y.shape)
    elif t == "mul_grad":
        x, y = V[op.in1("X")], V[op.in1("Y")]
        dout = V[op.in1("Out@GRAD")]
        xn = a.get("x_num_col_dims", 1)
        yn = a.get("y_num_col_dims", 1)
        x2 = x.reshape(int(np.prod(x.shape[:xn])), -1)
        y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
        d2 = dout.reshape(x2.shape[0], y2.shape[1])
        if op.out1("X@GRAD"):
            V[op.out1("X@GRAD")] = (d2 @ y2.T).reshape(x.shape)
        if op.out1("Y@GRAD"):
            V[op.out1("Y@GRAD")] = (x2.T @ d2).reshape(y.shape)
    elif t == "sgd":
        p = V[op.in1("Param")]
        g = V[op.in1("Grad")]
        lr = jnp.reshape(V[op.in1("LearningRate")], ())
        V[_out_req(op, "ParamOut")] = p - lr * g
    elif t == "momentum":
        p, g = V[op.in1("Param")], V[op.in1("Grad")]
        vel = V[op.in1("Velocity")]
        lr = jnp.reshape(V[op.in1("LearningRate")], ())
        mu = a.get("mu", 0.9)
        vel_out = mu * vel + g
        V[_out_req(op, "VelocityOut")] = vel_out
        V[_out_req(op, "ParamOut")] = (p - lr * (g + mu * vel_out)
                                  if a.get("use_nesterov", False)
                                  else p - lr * vel_out)
    elif t == "adam":
        p, g = V[op.in1("Param")], V[op.in1("Grad")]
        m1, m2 = V[op.in1("Moment1")], V[op.in1("Moment2")]
        b1p = jnp.reshape(V[op.in1("Beta1Pow")], ())
        b2p = jnp.reshape(V[op.in1("Beta2Pow")], ())
        lr = jnp.reshape(V[op.in1("LearningRate")], ())
        b1, b2 = a.get("beta1", 0.9), a.get("beta2", 0.999)
        eps = a.get("epsilon", 1e-8)
        m1n = b1 * m1 + (1.0 - b1) * g
        m2n = b2 * m2 + (1.0 - b2) * g * g
        # AdamFunctor: lr_t from the INPUT beta pows (beta^t at step t,
        # pows initialized to beta); pows advance on output
        lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
        V[_out_req(op, "ParamOut")] = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
        V[_out_req(op, "Moment1Out")] = m1n
        V[_out_req(op, "Moment2Out")] = m2n
        # fluid-1.x exports advanced the pows with separate scale ops,
        # so these outputs are optional
        if op.out1("Beta1PowOut"):
            V[op.out1("Beta1PowOut")] = b1p * b1
        if op.out1("Beta2PowOut"):
            V[op.out1("Beta2PowOut")] = b2p * b2
    else:
        raise NotImplementedError(
            f"imported op '{t}' has no TPU-native mapping yet "
            f"(inputs={list(op.inputs)}, attrs={list(op.attrs)})")


class PaddleProgram:
    """An imported reference program: block-0 interpreter over jnp ops."""

    def __init__(self, blocks: List[BlockDesc]):
        self.blocks = blocks
        self.params: Dict[str, np.ndarray] = {}
        b0 = blocks[0]
        self.feed_names = [op.out1("Out") for op in b0.ops
                           if op.type == "feed"]
        self.fetch_names = [op.in1("X") for op in b0.ops
                            if op.type == "fetch"]
        self.persistable_names = sorted(
            n for n, v in b0.vars.items()
            if v.persistable and v.type_id not in (9, 10))  # not feed/fetch
        # persistables some op WRITES (optimizer ParamOut/moments): run()
        # syncs these back so repeated runs train, like the reference
        # executor mutating its scope
        self._written_persistables = sorted(
            set(self.persistable_names) & set(_sub_block_writes(b0, blocks)))

    def persistable_names_current(self):
        """The LIVE parameter set (post-passes: folded constants included,
        pruned originals gone) — what the serializer writes."""
        return sorted(self.params)

    def load_combined_params(self, path: str):
        """A save_combine / save_inference_model(params_filename=...) blob:
        LoDTensor streams back-to-back, one per persistable var in sorted
        name order (io.py save_vars sorts for determinism)."""
        with open(path, "rb") as f:
            for name in self.persistable_names:
                arr = read_lod_tensor_stream(f)
                if arr is None:
                    raise ValueError(
                        f"params file ended before var {name!r}")
                self.params[name] = arr

    def load_separate_params(self, dirname: str):
        for name in self.persistable_names:
            with open(os.path.join(dirname, name), "rb") as f:
                arr = read_lod_tensor_stream(f)
            if arr is None:
                raise ValueError(f"param file for {name!r} is empty or "
                                 f"truncated")
            self.params[name] = arr

    def run(self, feed: Dict[str, np.ndarray],
            fetch_list: Optional[List[str]] = None):
        import jax.numpy as jnp

        V: Dict[str, object] = dict(self.params)
        V.update({k: jnp.asarray(v) for k, v in feed.items()})
        for op in self.blocks[0].ops:
            _run_op(op, V, jnp, self.blocks)
        # reference-executor scope semantics: optimizer writes to
        # persistables survive into the next run (training resumes)
        for n in self._written_persistables:
            if n in V:
                self.params[n] = np.asarray(V[n])
        names = fetch_list or self.fetch_names
        return [np.asarray(V[n]) for n in names]

    def as_fn(self):
        """(feed_dict) -> fetches as a pure function — wrap in jax.jit to
        compile the whole imported model into one XLA program. Control
        flow lowers structurally: while -> lax.while_loop,
        scalar conditional_block -> lax.cond (while_op.cc semantics with
        a traced carry)."""
        def fn(feed):
            import jax.numpy as jnp

            V = {k: jnp.asarray(v) for k, v in self.params.items()}
            V.update(feed)
            for op in self.blocks[0].ops:
                _run_op(op, V, jnp, self.blocks, traced=True)
            return [V[n] for n in self.fetch_names]

        return fn


def load_paddle_inference_model(dirname: str,
                                model_filename: str = "__model__",
                                params_filename: Optional[str] = None
                                ) -> PaddleProgram:
    """io.py load_inference_model analog for reference-format artifacts."""
    with open(os.path.join(dirname, model_filename), "rb") as f:
        prog = PaddleProgram(parse_program_desc(f.read()))
    if params_filename is not None:
        prog.load_combined_params(os.path.join(dirname, params_filename))
    elif prog.persistable_names:
        prog.load_separate_params(dirname)
    return prog

"""Minimal proto2 wire-format codec for the reference model format.

The reference serializes programs with protobuf
(paddle/fluid/framework/framework.proto) — ~6 small messages. Rather than
shipping generated protobuf code, this is a from-scratch wire codec
(https://protobuf.dev/programming-guides/encoding/): varint keys, four wire
types, schema applied by the caller. Enough to read AND write ProgramDesc /
VarDesc / OpDesc / VarType.TensorDesc.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

VARINT, I64, LEN, I32 = 0, 1, 2, 5


def read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def to_signed(v: int, bits: int = 64) -> int:
    return v - (1 << bits) if v >= 1 << (bits - 1) else v


def decode_fields(buf: bytes) -> Dict[int, List[Tuple[int, object]]]:
    """field_number -> [(wire_type, raw_value)...]; LEN values stay bytes."""
    fields: Dict[int, List[Tuple[int, object]]] = {}
    pos = 0
    while pos < len(buf):
        key, pos = read_varint(buf, pos)
        fno, wt = key >> 3, key & 7
        if wt == VARINT:
            v, pos = read_varint(buf, pos)
        elif wt == I64:
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == LEN:
            n, pos = read_varint(buf, pos)
            v = buf[pos:pos + n]
            pos += n
        elif wt == I32:
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        fields.setdefault(fno, []).append((wt, v))
    return fields


def get1(fields, fno, default=None):
    vals = fields.get(fno)
    return vals[0][1] if vals else default


def get_all(fields, fno):
    return [v for _, v in fields.get(fno, [])]


def get_repeated_varints(fields, fno, signed=True):
    """Repeated integers: proto2 default is unpacked (one VARINT field per
    element) but packed (one LEN blob) also appears; accept both."""
    out = []
    for wt, v in fields.get(fno, []):
        if wt == VARINT:
            out.append(to_signed(v) if signed else v)
        elif wt == LEN:
            pos = 0
            while pos < len(v):
                x, pos = read_varint(v, pos)
                out.append(to_signed(x) if signed else x)
    return out


def f32(raw: int) -> float:
    return struct.unpack("<f", struct.pack("<I", raw))[0]


def f64(raw: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", raw))[0]


# -- encoding (used to author reference-format artifacts, incl. tests) ------

def enc_varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_tag(fno: int, wt: int) -> bytes:
    return enc_varint((fno << 3) | wt)


def enc_int(fno: int, v: int) -> bytes:
    return enc_tag(fno, VARINT) + enc_varint(int(v))


def enc_bytes(fno: int, v) -> bytes:
    if isinstance(v, str):
        v = v.encode()
    return enc_tag(fno, LEN) + enc_varint(len(v)) + v


def enc_f32(fno: int, v: float) -> bytes:
    return enc_tag(fno, I32) + struct.pack("<f", float(v))


def enc_f64(fno: int, v: float) -> bytes:
    return enc_tag(fno, I64) + struct.pack("<d", float(v))

"""Serialize programs BACK to the reference model format.

The inverse of importer.py: parsed BlockDesc/OpDesc/VarDesc objects (plus
`PaddleProgram.params`) re-encode to `__model__` ProgramDesc bytes and a
combined persistables blob in the SerializeToStream layout — byte-compatible
with the reference's load_inference_model. The main use: import a reference
model, run the inference analysis passes (inference/passes.py), and hand the
OPTIMIZED model back to the reference ecosystem.
"""
from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

from . import importer, wire
from .wire import LEN, enc_bytes, enc_int, enc_tag, enc_varint

__all__ = ["serialize_program_desc", "write_lod_tensor_stream",
           "save_paddle_inference_model"]

# numpy dtype -> VarType.Type enum (inverse of importer.DTYPES)
DTYPE_ENUMS = {np.dtype(v): k for k, v in importer.DTYPES.items()}

LOD_TENSOR = 7


_msg = enc_bytes  # LEN-framed submessage == length-delimited bytes field


def _enc_attr(name: str, val, atype: int) -> bytes:
    A = importer
    out = enc_bytes(1, name) + enc_int(2, atype)
    if atype == A.A_INT:
        out += enc_int(3, int(val))
    elif atype == A.A_FLOAT:
        out += wire.enc_f32(4, float(val))
    elif atype == A.A_STRING:
        out += enc_bytes(5, val)
    elif atype == A.A_INTS:
        out += b"".join(enc_int(6, v) for v in val)
    elif atype == A.A_FLOATS:
        out += b"".join(wire.enc_f32(7, v) for v in val)
    elif atype == A.A_STRINGS:
        out += b"".join(enc_bytes(8, v) for v in val)
    elif atype == A.A_BOOL:
        out += enc_int(10, int(bool(val)))
    elif atype == A.A_BOOLS:
        out += b"".join(enc_int(11, int(bool(v))) for v in val)
    elif atype == A.A_BLOCK:
        out += enc_int(12, int(val))
    elif atype == A.A_LONG:
        out += enc_int(13, int(val))
    elif atype == A.A_BLOCKS:
        out += b"".join(enc_int(14, v) for v in val)
    elif atype == A.A_LONGS:
        out += b"".join(enc_int(15, v) for v in val)
    elif atype == A.A_FLOAT64S:
        out += b"".join(wire.enc_f64(16, v) for v in val)
    else:
        raise ValueError(f"unknown AttrType {atype} for attr {name!r}")
    return out


def _enc_op(op) -> bytes:
    out = b""
    for param, args in op.inputs.items():
        out += _msg(1, enc_bytes(1, param)
                    + b"".join(enc_bytes(2, a) for a in args))
    for param, args in op.outputs.items():
        out += _msg(2, enc_bytes(1, param)
                    + b"".join(enc_bytes(2, a) for a in args))
    out += enc_bytes(3, op.type)
    for name, val in op.attrs.items():
        atype = getattr(op, "attr_types", {}).get(name)
        if atype is None:  # attr synthesized by a pass: infer the type
            atype = _infer_attr_type(val)
        out += _msg(4, _enc_attr(name, val, atype))
    return out


def _infer_attr_type(val) -> int:
    A = importer
    if isinstance(val, bool):
        return A.A_BOOL
    if isinstance(val, int):
        return A.A_INT
    if isinstance(val, float):
        return A.A_FLOAT
    if isinstance(val, str):
        return A.A_STRING
    if isinstance(val, (list, tuple)):
        if all(isinstance(v, bool) for v in val):
            return A.A_BOOLS
        if all(isinstance(v, int) for v in val):
            return A.A_INTS
        if all(isinstance(v, float) for v in val):
            return A.A_FLOATS
        if all(isinstance(v, str) for v in val):
            return A.A_STRINGS
    raise ValueError(f"cannot infer AttrType for {val!r}")


def _tensor_desc(dtype_enum: int, dims) -> bytes:
    return enc_int(1, dtype_enum) + b"".join(enc_int(2, d) for d in dims)


def _enc_var(var) -> bytes:
    vt = enc_int(1, var.type_id)
    if var.dtype_enum is not None:
        vt += _msg(3, _msg(1, _tensor_desc(var.dtype_enum,
                                           var.shape or [])))
    out = enc_bytes(1, var.name) + _msg(2, vt)
    if var.persistable:
        out += enc_int(3, 1)
    return out


def _synth_var(name: str, arr: np.ndarray):
    """VarDesc for a parameter a pass created (folded constants)."""
    v = importer.VarDesc.__new__(importer.VarDesc)
    v.name = name
    v.persistable = True
    v.type_id = LOD_TENSOR
    v.dtype = arr.dtype.type
    v.dtype_enum = DTYPE_ENUMS[np.dtype(arr.dtype)]
    v.shape = list(arr.shape)
    return v


def serialize_program_desc(blocks) -> bytes:
    out = b""
    for b in blocks:
        body = enc_int(1, b.idx) + enc_int(2, b.parent_idx)
        body += b"".join(_msg(3, _enc_var(v)) for v in b.vars.values())
        body += b"".join(_msg(4, _enc_op(op)) for op in b.ops)
        out += _msg(1, body)
    return out


def write_lod_tensor_stream(f, arr: np.ndarray):
    """SerializeToStream layout (lod_tensor.cc:190): u32 version, u64
    lod_level(0), then TensorToStream."""
    arr = np.ascontiguousarray(arr)
    desc = _tensor_desc(DTYPE_ENUMS[np.dtype(arr.dtype)], arr.shape)
    f.write(struct.pack("<I", 0))
    f.write(struct.pack("<Q", 0))
    f.write(struct.pack("<I", 0))
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(arr.tobytes())


def save_paddle_inference_model(prog, dirname: str,
                                model_filename: str = "__model__",
                                params_filename: Optional[str] = "__params__"
                                ) -> str:
    """Write a PaddleProgram as a reference-format artifact. Block-0 var
    descriptors are synced to the program's CURRENT parameter set (passes
    may have folded new constants in or pruned originals out), so the
    written model round-trips through either loader."""
    import copy

    b0 = prog.blocks[0]
    live = set(prog.params)
    # drop descriptors of pruned params; keep everything non-persistable.
    # All adjustments happen on COPIES — saving must not mutate the
    # in-memory program (its cached persistable_names and descriptors
    # stay consistent for further passes / re-serialization).
    kept = {n: v for n, v in b0.vars.items()
            if not v.persistable or v.type_id != LOD_TENSOR or n in live}
    for name in sorted(live):
        arr = np.asarray(prog.params[name])
        existing = kept.get(name)
        if existing is None:
            kept[name] = _synth_var(name, arr)
        else:
            # a pass promoted an intermediate to a constant: its descriptor
            # must become persistable (and carry concrete shape/dtype) or
            # the loader won't read it back from the params blob
            v = copy.copy(existing)
            v.persistable = True
            v.type_id = LOD_TENSOR
            v.dtype_enum = DTYPE_ENUMS[np.dtype(arr.dtype)]
            v.shape = list(arr.shape)
            kept[name] = v
    b0_view = copy.copy(b0)
    b0_view.vars = kept

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename), "wb") as f:
        f.write(serialize_program_desc([b0_view] + list(prog.blocks[1:])))
    if params_filename is not None:
        with open(os.path.join(dirname, params_filename), "wb") as f:
            for name in sorted(prog.persistable_names_current()):
                write_lod_tensor_stream(f, np.asarray(prog.params[name]))
    else:
        for name in prog.persistable_names_current():
            with open(os.path.join(dirname, name), "wb") as f:
                write_lod_tensor_stream(f, np.asarray(prog.params[name]))
    return os.path.join(dirname, model_filename)

"""Wide&Deep CTR model — the recommendation-scale PS flagship (ISSUE 20).

Reference: the Wide&Deep net the reference exercises through its PS tests
(fleet/parameter_server/*wide_deep*): per-slot sparse id features looked up
in a PS-hosted embedding table, a wide (linear) arm over the same embedded
features and a deep MLP arm, summed into one CTR logit.

TPU-native split: ONLY the dense arms live here. The sparse embedding rows
arrive pre-gathered as one `[batch, slots*dim]` device array — pulled by
`distributed/ps/pipeline.py` (sharded/cached/quantized pull) or by the
`heter_cache` tiers — so the model composes with the eager path, the
`CompiledPassStep` pass path, and the ISSUE-20 `PsTrainStep` without
knowing where rows come from. Promoted out of examples/wide_deep_ps.py so
the bench, the pipeline, and the tests drive one definition.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from ..nn import functional as F
from ..nn.layer.activation import ReLU
from ..nn.layer.common import Linear
from ..nn.layer.container import Sequential
from ..nn.layer.layers import Layer

__all__ = ["WideDeep", "wide_deep_loss", "ctr_batches", "zipf_ids"]


class WideDeep(Layer):
    """Dense arms of Wide&Deep over pre-gathered embedding rows.

    forward(flat_emb [batch, slots*dim]) -> logits [batch, 1]; the wide
    arm is a single linear over the embedded features (the reference's
    first-order term, here sharing the embedding with the deep arm — the
    common "wide&deep with shared embeddings" shape) and the deep arm an
    MLP; the two sum into the CTR logit.
    """

    def __init__(self, slots: int, dim: int,
                 hidden: Sequence[int] = (64, 32)):
        super().__init__()
        self.slots = int(slots)
        self.dim = int(dim)
        in_f = self.slots * self.dim
        self.wide = Linear(in_f, 1)
        layers, prev = [], in_f
        for h in hidden:
            layers += [Linear(prev, int(h)), ReLU()]
            prev = int(h)
        layers.append(Linear(prev, 1))
        self.deep = Sequential(*layers)

    def forward(self, flat_emb):
        return self.wide(flat_emb) + self.deep(flat_emb)


def wide_deep_loss(logits, labels):
    """BCE-with-logits over the [batch, 1] CTR logits (loss_fn contract of
    CompiledPassStep / PsTrainStep: (output, labels) -> scalar Tensor)."""
    return F.binary_cross_entropy_with_logits(
        logits.reshape([-1]), labels.reshape([-1]))


def zipf_ids(rs: np.random.RandomState, vocab: int, size, alpha: float = 1.1):
    """Zipfian sparse ids over [0, vocab): rank-frequency skew ~ r^-alpha,
    the key-traffic shape recommendation workloads actually see (a few hot
    ids dominate; the long tail thrashes caches). alpha<=0 degrades to
    uniform."""
    if alpha <= 0:
        return rs.randint(0, vocab, size).astype(np.uint64)
    w = 1.0 / np.arange(1, vocab + 1, dtype=np.float64) ** alpha
    w /= w.sum()
    # rank r maps to id r-1: id 0 is the hottest key, deterministically
    return rs.choice(vocab, size=size, p=w).astype(np.uint64)


def ctr_batches(steps: int, batch: int, slots: int, vocab: int,
                alpha: float = 1.1, seed: int = 0):
    """Synthetic CTR stream: (ids [batch, slots] uint64, labels [batch]
    f32) with Zipfian ids and labels from a fixed random linear teacher —
    learnable, so convergence-parity tests have a loss that moves."""
    rs = np.random.RandomState(seed)
    true_w = rs.randn(vocab)
    out = []
    for _ in range(int(steps)):
        ids = zipf_ids(rs, vocab, (batch, slots), alpha)
        labels = (true_w[ids.astype(np.int64)].sum(1) > 0).astype(np.float32)
        out.append((ids, labels))
    return out

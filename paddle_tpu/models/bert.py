"""BERT — bidirectional encoder, the BASELINE config-3 model family.

Reference precedent: the BERT used by the fleet/AMP baselines (PaddleNLP
BertModel/BertForPretraining over nn.TransformerEncoder —
python/paddle/nn/layer/transformer.py is the in-repo encoder it builds on).

TPU-native design mirrors models/gpt.py: ONE logical model whose parallelism
is parameter PartitionSpecs over the hybrid mesh (TP: q/k/v/fc1 column-
sharded on 'model', out/fc2 row-sharded; vocab embedding row-sharded);
attention rides the pallas flash kernel through
F.scaled_dot_product_attention when unmasked on TPU; everything trains via
the fused TrainStep with AMP bf16 (BASELINE config 3: fleet + AMP)."""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np
from jax.sharding import PartitionSpec as P

from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "BertPretrainingCriterion", "bert_presets"]

MODEL_AXIS = "model"


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pad_token_id: int = 0
    # >0: forward(..., masked_lm_labels=...) computes the MLM loss via
    # chunked fused linear+CE over the tied embedding (logits never
    # materialized); the NSP logits are returned alongside
    fused_loss_chunk: int = 0

    @property
    def ffn(self):
        return self.intermediate_size or 4 * self.hidden_size


def bert_presets(name: str, **overrides) -> BertConfig:
    presets = {
        "bert-test": dict(vocab_size=256, hidden_size=64, num_layers=2,
                          num_heads=4, max_position_embeddings=64),
        "bert-base": dict(),
        "bert-large": dict(hidden_size=1024, num_layers=24, num_heads=16),
    }
    cfg = dict(presets[name])
    cfg.update(overrides)
    return BertConfig(**cfg)


def _mark_tp(layer: Linear, spec):
    layer.weight.dist_spec = spec
    layer.weight.is_distributed = True
    if layer.bias is not None and spec == P(None, MODEL_AXIS):
        layer.bias.dist_spec = P(MODEL_AXIS)
        layer.bias.is_distributed = True


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = Normal(0.0, cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size)
        for e in (self.word_embeddings, self.position_embeddings,
                  self.token_type_embeddings):
            e.weight.set_value((np.random.RandomState(0).randn(
                *e.weight.shape) * cfg.initializer_range).astype("float32"))
        # vocab-parallel word embedding (mp_layers.py VocabParallelEmbedding)
        self.word_embeddings.weight.dist_spec = P(MODEL_AXIS, None)
        self.word_embeddings.weight.is_distributed = True
        self.layer_norm = LayerNorm(cfg.hidden_size,
                                    epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from .. import tensor as ops

        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.arange(s, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        from .. import tensor as ops

        return ops.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """Embeddings → TransformerEncoder → pooler. Returns
    (sequence_output [b, s, H], pooled_output [b, H])."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_heads, config.ffn,
            dropout=config.dropout, activation="gelu",
            attn_dropout=config.attn_dropout, act_dropout=config.dropout,
            normalize_before=False)
        self.encoder = TransformerEncoder(enc_layer, config.num_layers)
        self.pooler = BertPooler(config)
        self._mark_tensor_parallel()

    def _mark_tensor_parallel(self):
        """Megatron specs on every encoder block (gpt.py _block_shapes
        equivalents): q/k/v + fc1 column-sharded, out + fc2 row-sharded."""
        for blk in self.encoder.layers:
            attn = blk.self_attn
            for proj in (attn.q_proj, attn.k_proj, attn.v_proj):
                _mark_tp(proj, P(None, MODEL_AXIS))
            _mark_tp(attn.out_proj, P(MODEL_AXIS, None))
            _mark_tp(blk.linear1, P(None, MODEL_AXIS))
            _mark_tp(blk.linear2, P(MODEL_AXIS, None))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(x, src_mask=attention_mask)
        return seq, self.pooler(seq)


class BertForPretraining(Layer):
    """MLM head (transform + tied decoder) + NSP head
    (BertPretrainingHeads in the reference stack)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.config = config
        h = config.hidden_size
        self.transform = Linear(h, h)
        self.transform_norm = LayerNorm(h, epsilon=config.layer_norm_eps)
        self.nsp = Linear(h, 2)
        from ..framework.tensor import Parameter

        self.mlm_bias = self.create_parameter(
            shape=[config.vocab_size], is_bias=True)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, masked_lm_labels=None):
        from ..framework.autograd import call_op
        import jax.numpy as jnp

        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask)
        x = F.gelu(self.transform(seq))
        x = self.transform_norm(x)
        w = self.bert.embeddings.word_embeddings.weight
        if masked_lm_labels is not None:
            # labels given → (mlm_loss, nsp_logits); ALL negative labels
            # mark unmasked positions (BertPretrainingCriterion's
            # `lbl >= 0` convention, covering both -1 and HF's -100)
            from .. import where as paddle_where
            from ..framework.tensor import to_tensor

            flat_lbl = masked_lm_labels.reshape([-1])
            flat_lbl = paddle_where(flat_lbl < 0,
                                    to_tensor(-1, dtype="int64"), flat_lbl)
            h = x.reshape([-1, self.config.hidden_size])
            if self.config.fused_loss_chunk > 0:
                # fused chunked linear+CE: logits never materialized
                from ..incubate.nn.functional import (
                    fused_linear_cross_entropy,
                )

                mlm_loss = fused_linear_cross_entropy(
                    h, w, flat_lbl, bias=self.mlm_bias,
                    vocab_chunk=self.config.fused_loss_chunk,
                    ignore_index=-1, transposed_weight=True)
            else:
                def full_loss(h_, w_, b_, lbl_):
                    import jax

                    lg = (h_ @ w_.T + b_).astype(jnp.float32)
                    lse = jax.nn.logsumexp(lg, axis=-1)
                    picked = jnp.take_along_axis(
                        lg, jnp.maximum(lbl_, 0)[:, None], axis=-1)[:, 0]
                    mask = (lbl_ >= 0).astype(jnp.float32)
                    return jnp.sum((lse - picked) * mask) / jnp.maximum(
                        jnp.sum(mask), 1.0)

                mlm_loss = call_op(full_loss, h, w, self.mlm_bias,
                                   flat_lbl, op_name="mlm_loss")
            return mlm_loss, self.nsp(pooled)
        logits = call_op(lambda h_, w_, b_: h_ @ w_.T + b_, x, w,
                         self.mlm_bias, op_name="mlm_logits")
        return logits, self.nsp(pooled)


class BertPretrainingCriterion(Layer):
    """Masked-LM loss (over masked positions) + NSP loss
    (reference BertPretrainingCriterion)."""

    def forward(self, prediction_scores, nsp_scores, masked_lm_labels,
                next_sentence_labels, masked_lm_weights=None):
        from ..framework.autograd import call_op
        import jax
        import jax.numpy as jnp

        def fn(lg, nsp, lbl, nsl, *w):
            lg = lg.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(
                lg, jnp.maximum(lbl, 0)[..., None], axis=-1)[..., 0]
            nll = lse - picked
            mask = (lbl >= 0).astype(jnp.float32)
            if w:
                mask = mask * w[0].astype(jnp.float32)
            mlm = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            ns = nsp.astype(jnp.float32)
            ns_lse = jax.nn.logsumexp(ns, axis=-1)
            ns_pick = jnp.take_along_axis(
                ns, nsl.reshape(-1, 1), axis=-1)[..., 0]
            return mlm + jnp.mean(ns_lse - ns_pick)

        args = [prediction_scores, nsp_scores, masked_lm_labels,
                next_sentence_labels]
        if masked_lm_weights is not None:
            args.append(masked_lm_weights)
        return call_op(fn, *args, op_name="bert_pretraining_loss")

"""paddle_tpu.models — flagship model families.

The reference keeps GPT/BERT in PaddleNLP and exercises them through fleet
hybrid-parallel tests (python/paddle/fluid/tests/unittests/hybrid_parallel_*);
BASELINE.md configs 3/4 name BERT-base and GPT-1.3B. These are the TPU-native
flagships: built from paddle_tpu.nn + fleet parallel layers, with scan-over-
layers pipeline mode and hybrid dp/tp/pp/sp sharding specs.
"""
from .gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTModel, GPTPretrainingCriterion, gpt_presets,
    gpt_1f1b_grad_fn, gpt_1f1b_train_step,
)
from .bert import (  # noqa: F401
    BertConfig, BertForPretraining, BertModel, BertPretrainingCriterion,
    bert_presets,
)
from .wide_deep import (  # noqa: F401
    WideDeep, wide_deep_loss, ctr_batches, zipf_ids,
)

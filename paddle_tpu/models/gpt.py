"""GPT — decoder-only LM, the flagship hybrid-parallel model.

Reference precedent: the GPT used by the fleet hybrid tests
(unittests/hybrid_parallel_gpt_*.py via PaddleNLP) built on
meta_parallel/parallel_layers/mp_layers.py (Vocab/Column/RowParallelLinear) and
pp_layers.py (PipelineLayer). TPU-native design:

- ONE logical model; parallelism is carried by PartitionSpecs on parameters and
  sharding constraints on activations over the hybrid mesh axes
  [data, pipe, sharding, sep, model] (distributed/mesh.py). GSPMD emits the
  Megatron collectives; the reference's explicit c_* ops dissolve.
- TP: fused qkv + fc1 are column-sharded ('model'), out-proj + fc2 row-sharded;
  vocab embedding row-sharded; logits stay vocab-sharded into the loss
  (reference: c_softmax_with_cross_entropy).
- PP: `mode="scan"` stacks the L identical blocks on a leading 'layers' dim
  sharded over 'pipe' and runs them with lax.scan — per-stage weights live on
  their pipe group only (reference SectionWorker/PipelineLayer, re-designed
  as SPMD scan instead of p2p 1F1B).
- SP: activations' sequence dim sharded over 'sep'; attention runs ring
  attention over 'sep' (net-new vs reference, SURVEY.md §5 long-context gap).
- Recompute: jax.checkpoint around each block (reference:
  fleet/utils/recompute.py).
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed import collective as coll
from ..distributed import mesh as mesh_mod
from ..framework import dtype as dtype_mod
from ..framework.autograd import call_op
from ..framework.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.common import Dropout, Embedding
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from jax.sharding import PartitionSpec as P

BATCH_AXES = ("data", "sharding")  # batch is sharded over dp × zero-dp
SEQ_AXIS = "sep"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 1024
    dropout: float = 0.0
    attn_dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    mode: str = "loop"  # "loop" (unrolled blocks) | "scan" (pipe-stacked)
    recompute: bool = False
    # per-layer activation policy ("none" | "remat" | "offload"), the
    # planner-chosen refinement of the boolean `recompute` (ISSUE 15):
    # length num_layers, or layers-per-stage for the pipelined path (a
    # full-length vector must then tile uniformly across stages — the
    # schedule is ONE SPMD program, stages cannot differ). None defers to
    # `recompute` (True = all-"remat"). "offload" saves the block input in
    # host memory (jax.checkpoint whose carried residual lives in the
    # offload tier; see distributed/pipeline/memory_plan.py for when that
    # buys real bytes).
    recompute_policy: Optional[tuple] = None
    sequence_parallel: bool = False
    use_ring_attention: bool = False
    # 'sep'-axis SP via all_to_all head/sequence swap instead of the ring
    # (DeepSpeed-Ulysses scheme; heads must divide by sep degree)
    use_ulysses_attention: bool = False
    use_flash_attention: bool = True  # pallas kernel on TPU when shapes allow
    pp_microbatches: int = 0  # pipeline micro-batches (0 = pipe degree)
    # >0: forward(input_ids, labels=...) computes the LM loss by chunked
    # fused linear+CE over the tied embedding — the [b*s, vocab] logits are
    # never materialized (incubate fused_linear_cross_entropy)
    fused_loss_chunk: int = 0
    dtype: str = "float32"

    def __post_init__(self):
        if self.use_ring_attention and self.use_ulysses_attention:
            raise ValueError(
                "use_ring_attention and use_ulysses_attention are mutually "
                "exclusive sequence-parallel schemes — pick one")
        if self.recompute_policy is not None:
            pol = tuple(self.recompute_policy)
            bad = [p for p in pol if p not in ("none", "remat", "offload")]
            if bad:
                raise ValueError(
                    f"recompute_policy entries must be one of "
                    f"none/remat/offload, got {bad}")
            if self.num_layers % max(1, len(pol)):
                raise ValueError(
                    f"recompute_policy length {len(pol)} does not tile "
                    f"num_layers={self.num_layers}")
            self.recompute_policy = pol

    @property
    def ffn(self):
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


def gpt_presets(name: str, **overrides) -> GPTConfig:
    presets = {
        "gpt-test": dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_heads=4, max_position_embeddings=128),
        "gpt-125m": dict(vocab_size=50304, hidden_size=768, num_layers=12,
                         num_heads=12, max_position_embeddings=1024),
        "gpt-350m": dict(vocab_size=50304, hidden_size=1024, num_layers=24,
                         num_heads=16, max_position_embeddings=1024),
        "gpt-760m": dict(vocab_size=50304, hidden_size=1536, num_layers=24,
                         num_heads=16, max_position_embeddings=2048),
        "gpt-1.3b": dict(vocab_size=50304, hidden_size=2048, num_layers=24,
                         num_heads=16, max_position_embeddings=2048),
    }
    cfg = dict(presets[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


# --------------------------------------------------------------------------
# pure block math, shared by loop and scan modes
# --------------------------------------------------------------------------

def _constrain_val(v, *spec):
    m = mesh_mod.get_mesh()
    if m is None:
        return v
    # axes the surrounding trace maps manually (a shard_map body — e.g.
    # TrainStep's explicit-SPMD quantized-grad path) cannot be constrained
    # again: the body already sees its per-device block
    manual = mesh_mod.manual_axis_names()

    def keep(a):
        return a in m.axis_names and a not in manual

    spec = tuple(
        (s if keep(s) else None) if isinstance(s, str)
        else (tuple(a for a in s if keep(a)) or None)
        if isinstance(s, tuple) else s
        for s in spec
    )
    if not any(s is not None for s in spec):
        return v
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(v, NamedSharding(m, P(*spec)))


def _flash_sharded(q, k, v):
    """Pallas flash kernel, wrapped in shard_map when a mesh is active so the
    custom call stays SPMD (GSPMD can't partition a pallas_call on its own —
    without this it would all-gather the head-sharded q/k/v). The wrapping
    lives in ops.flash_attention_val_auto, shared with the nn sdpa path."""
    from ..ops.flash_attention import flash_attention_val_auto

    return flash_attention_val_auto(q, k, v, causal=True)


def _attention_val(q, k, v, cfg: GPTConfig):
    """[b, s, n, d] causal attention at value level."""
    if cfg.use_ring_attention and mesh_mod.axis_size(SEQ_AXIS) > 1:
        from ..distributed.ring_attention import ring_attention_val

        return ring_attention_val(q, k, v, axis=SEQ_AXIS, causal=True)
    if cfg.use_ulysses_attention and mesh_mod.axis_size(SEQ_AXIS) > 1:
        from ..distributed.ulysses import ulysses_attention_val

        return ulysses_attention_val(
            q, k, v, axis=SEQ_AXIS, causal=True,
            use_flash=cfg.use_flash_attention and cfg.attn_dropout == 0.0)
    from ..framework.target import target_platform

    if (cfg.use_flash_attention and cfg.attn_dropout == 0.0
            and target_platform() == "tpu"):
        from ..ops.flash_attention import flash_attention_sharded_ok

        if flash_attention_sharded_ok(q.shape):
            return _flash_sharded(q, k, v)
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    ql, kl = logits.shape[-2], logits.shape[-1]
    causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
    logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_apply(pd: dict, x, cfg: GPTConfig):
    """One transformer block. pd maps name → raw array (one layer's slice)."""
    b, s, h = x.shape
    n, d = cfg.num_heads, cfg.head_dim
    eps = cfg.layer_norm_epsilon

    def ln(v, w, bi):
        mu = jnp.mean(v.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
        return (out * w + bi).astype(v.dtype)

    # --- attention
    hn = ln(x, pd["ln1_w"], pd["ln1_b"])
    qkv = jnp.einsum("bsh,hcj->bscj", hn, pd["qkv_w"]) + pd["qkv_b"]
    qkv = qkv.reshape(b, s, 3, n, d)  # [b,s,3,H] col-sharded on 'model'
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    q = _constrain_val(q, BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
    k = _constrain_val(k, BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
    v = _constrain_val(v, BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
    attn = _attention_val(q, k, v, cfg)
    attn = attn.reshape(b, s, h)
    y = attn @ pd["out_w"] + pd["out_b"]  # row-sharded: GSPMD allreduces
    x = x + y
    x = _constrain_val(x, BATCH_AXES, SEQ_AXIS, None)

    # --- mlp
    hn = ln(x, pd["ln2_w"], pd["ln2_b"])
    z = hn @ pd["fc1_w"] + pd["fc1_b"]
    z = jax.nn.gelu(z, approximate=True)
    z = z @ pd["fc2_w"] + pd["fc2_b"]
    x = x + z
    return _constrain_val(x, BATCH_AXES, SEQ_AXIS, None)


def _block_apply_manual(pd: dict, x, cfg: GPTConfig, mesh):
    """One transformer block INSIDE a shard_map manual region (the pipeline
    path). Explicit Megatron TP — qkv/fc1 are column-sharded local slices,
    out/fc2 row-sharded with a psum over 'model' (the c_allreduce_sum the
    reference emits, mp_layers.py) — and ring attention over 'sep'."""
    b, s, _ = x.shape
    d = cfg.head_dim
    eps = cfg.layer_norm_epsilon
    has_model = MODEL_AXIS in mesh.axis_names and mesh.shape[MODEL_AXIS] > 1
    has_sep = SEQ_AXIS in mesh.axis_names and mesh.shape[SEQ_AXIS] > 1

    def ln(v, w, bi):
        mu = jnp.mean(v.astype(jnp.float32), axis=-1, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
        return (out * w + bi).astype(v.dtype)

    hn = ln(x, pd["ln1_w"], pd["ln1_b"])
    qkv = jnp.einsum("bsh,hcj->bscj", hn, pd["qkv_w"]) + pd["qkv_b"]
    n_loc = qkv.shape[-1] // d                    # local head count (H/mp)/d
    qkv = qkv.reshape(b, s, 3, n_loc, d)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if has_sep:
        if cfg.use_ulysses_attention:
            from ..distributed.ulysses import ulysses_attention_manual

            attn = ulysses_attention_manual(
                q, k, v, SEQ_AXIS, causal=True,
                use_flash=(cfg.use_flash_attention
                           and cfg.attn_dropout == 0.0))
        else:
            from ..distributed.ring_attention import ring_attention_manual

            attn = ring_attention_manual(q, k, v, SEQ_AXIS,
                                         mesh.shape[SEQ_AXIS], causal=True)
    else:
        attn = None
        from ..framework.target import target_platform

        if (cfg.use_flash_attention and cfg.attn_dropout == 0.0
                and target_platform() == "tpu"):
            from ..ops.flash_attention import (
                flash_attention_supported, flash_attention_val,
            )

            if flash_attention_supported(q.shape):
                attn = flash_attention_val(q, k, v, causal=True)
        if attn is None:
            scale = 1.0 / math.sqrt(d)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            causal = jnp.tril(jnp.ones((s, s), dtype=bool))
            logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(v.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    attn = attn.reshape(b, s, n_loc * d)
    y = attn @ pd["out_w"]                        # row-sharded: partial sums
    if has_model:
        y = coll.in_trace_psum(y, MODEL_AXIS)
    x = x + y + pd["out_b"]

    hn = ln(x, pd["ln2_w"], pd["ln2_b"])
    z = hn @ pd["fc1_w"] + pd["fc1_b"]
    z = jax.nn.gelu(z, approximate=True)
    z = z @ pd["fc2_w"]
    if has_model:
        z = coll.in_trace_psum(z, MODEL_AXIS)
    return x + z + pd["fc2_b"]


_BLOCK_PARAMS = ("ln1_w", "ln1_b", "qkv_w", "qkv_b", "out_w", "out_b",
                 "ln2_w", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b")


def _block_shapes(cfg: GPTConfig):
    h, f = cfg.hidden_size, cfg.ffn
    return {
        "ln1_w": ([h], None), "ln1_b": ([h], None),
        # qkv packed as [h, 3(q|k|v), h] so a 'model'-axis shard of the LAST
        # dim slices q, k and v heads consistently (a flat [h, 3h] chunk
        # would mix all of q with part of k under manual TP)
        "qkv_w": ([h, 3, h], P(None, None, MODEL_AXIS)),
        "qkv_b": ([3, h], P(None, MODEL_AXIS)),
        "out_w": ([h, h], P(MODEL_AXIS, None)), "out_b": ([h], None),
        "ln2_w": ([h], None), "ln2_b": ([h], None),
        "fc1_w": ([h, f], P(None, MODEL_AXIS)), "fc1_b": ([f], P(MODEL_AXIS)),
        "fc2_w": ([f, h], P(MODEL_AXIS, None)), "fc2_b": ([h], None),
    }


def _block_init(name, shape, cfg: GPTConfig, rs: np.random.RandomState):
    if name.startswith("ln") and name.endswith("_w"):
        return np.ones(shape, dtype="float32")
    if name.endswith("_b"):
        return np.zeros(shape, dtype="float32")
    std = cfg.initializer_range
    if name in ("out_w", "fc2_w"):
        # GPT-2 residual-projection scaling: std / sqrt(2*L)
        std = std / math.sqrt(2.0 * cfg.num_layers)
    return (rs.randn(*shape) * std).astype("float32")


def _resolve_policies(cfg: GPTConfig, n_layers: int):
    """Per-layer activation policies for a stack of `n_layers` scanned
    blocks (the whole model, or one pipeline stage's slice). A
    full-model-length vector collapses onto a stage slice only when it
    tiles uniformly — the SPMD schedule runs ONE stage program."""
    pol = cfg.recompute_policy
    if pol is None:
        return ("remat" if cfg.recompute else "none",) * n_layers
    if len(pol) == n_layers:
        return tuple(pol)
    if len(pol) % n_layers == 0:
        # full-length vector over a stage slice: must tile uniformly
        for s in range(0, len(pol), n_layers):
            if tuple(pol[s:s + n_layers]) != tuple(pol[:n_layers]):
                raise ValueError(
                    f"recompute_policy {pol} varies across pipeline "
                    f"stages of {n_layers} layers; the SPMD schedule "
                    f"runs one stage program — use a uniform per-stage "
                    f"vector")
        return tuple(pol[:n_layers])
    if n_layers % len(pol) == 0:
        return tuple(pol) * (n_layers // len(pol))
    raise ValueError(
        f"recompute_policy length {len(pol)} does not tile {n_layers} "
        f"layers")


def _policy_step(apply_full, policy: str):
    """Wrap one scanned-block step `apply_full(carry, slices) -> carry`
    with its activation policy. "remat" is the classic jax.checkpoint;
    "offload" additionally parks the saved block input in the offload
    memory space, so the residual jax keeps for the backward is the
    host-resident copy (the device copy is transient)."""
    if policy == "remat":
        return jax.checkpoint(apply_full)
    if policy == "offload":
        from ..distributed.pipeline.memory_plan import _offload_kind
        from ..distributed.pipeline.schedule import _to_memory_kind

        kind = _offload_kind()
        try:
            dev_kind = jax.devices()[0].default_memory().kind
        except Exception:
            dev_kind = None
        fetch = dev_kind if (dev_kind and dev_kind != kind) else None

        def run(carry, slices):
            c_host = _to_memory_kind(carry, kind)

            def inner(c2, sl):
                return apply_full(_to_memory_kind(c2, fetch), sl)

            return jax.checkpoint(inner)(c_host, slices)

        return run
    return apply_full


def _scan_policied(apply_full, stacked, x, policies):
    """lax.scan the stacked block params over `x`, one scan segment per
    contiguous run of equal policy — the lowering of the planner's
    per-layer vector onto scanned blocks (a single scan has one body, so
    heterogeneous policies become consecutive homogeneous scans)."""
    runs = []
    for p in policies:
        if runs and runs[-1][0] == p:
            runs[-1][1] += 1
        else:
            runs.append([p, 1])
    off = 0
    for pol, cnt in runs:
        seg = tuple(a[off:off + cnt] for a in stacked)
        step = _policy_step(apply_full, pol)
        x, _ = jax.lax.scan(lambda c, s: (step(c, s), None), x, seg)
        off += cnt
    return x


class GPTDecoderLayer(Layer):
    """Loop-mode block: individually named parameters, TP dist_specs."""

    def __init__(self, cfg: GPTConfig, rs: np.random.RandomState):
        super().__init__()
        self.cfg = cfg
        dt = dtype_mod.convert_dtype(cfg.dtype)
        for name, (shape, spec) in _block_shapes(cfg).items():
            p = Tensor(_block_init(name, shape, cfg, rs), dtype=dt)
            param = _as_parameter(p, spec)
            setattr(self, name, param)

    def forward(self, x):
        pd = {n: getattr(self, n)._value for n in _BLOCK_PARAMS}

        def fn(xv, *pvals):
            d = dict(zip(_BLOCK_PARAMS, pvals))
            body = partial(_block_apply, d, cfg=self.cfg)
            if self.cfg.recompute:
                body = jax.checkpoint(body)
            return body(xv)

        return call_op(fn, x, *[getattr(self, n) for n in _BLOCK_PARAMS],
                       op_name="gpt_block")


def _as_parameter(t: Tensor, spec):
    from ..framework.tensor import Parameter

    p = Parameter(t._value, trainable=True)
    if spec is not None:
        p.dist_spec = spec
        p.is_distributed = True
    return p


class GPTScanDecoder(Layer):
    """Scan-mode stack: each block parameter stacked on a leading 'layers'
    dim sharded over 'pipe' — pipeline-parallel weight placement, executed as
    lax.scan (reference PipelineLayer re-designed SPMD)."""

    def __init__(self, cfg: GPTConfig, rs: np.random.RandomState):
        super().__init__()
        self.cfg = cfg
        dt = dtype_mod.convert_dtype(cfg.dtype)
        L = cfg.num_layers
        shapes = _block_shapes(cfg)
        # draw layer-major so loop and scan modes share bit-identical init
        per_layer = [
            {name: _block_init(name, shape, cfg, rs)
             for name, (shape, _) in shapes.items()}
            for _ in range(L)
        ]
        for name, (shape, spec) in shapes.items():
            stacked = np.stack([per_layer[l][name] for l in range(L)])
            base = spec if spec is not None else P(*([None] * len(shape)))
            pipe_spec = P(PIPE_AXIS, *base)
            setattr(self, name, _as_parameter(Tensor(stacked, dtype=dt), pipe_spec))

    def forward(self, x):
        cfg = self.cfg
        mesh = mesh_mod.get_mesh()
        if mesh is not None and mesh_mod.axis_size(PIPE_AXIS) > 1:
            return self._forward_pipelined(x, mesh)

        def fn(xv, *stacked):
            def apply_full(carry, layer_slices):
                d = dict(zip(_BLOCK_PARAMS, layer_slices))
                return _block_apply(d, carry, cfg=cfg)

            return _scan_policied(apply_full, tuple(stacked), xv,
                                  _resolve_policies(cfg, cfg.num_layers))

        return call_op(fn, x, *[getattr(self, n) for n in _BLOCK_PARAMS],
                       op_name="gpt_scan_stack")

    def _forward_pipelined(self, x, mesh):
        """Micro-batched collective-permute pipeline over the 'pipe' axis
        (distributed/pipeline.py) — the reference's 1F1B train_batch schedule
        (pipeline_parallel.py:80-150) as one SPMD program."""
        from ..distributed.pipeline import pipeline_spmd

        cfg = self.cfg
        shapes = _block_shapes(cfg)
        specs = []
        for name in _BLOCK_PARAMS:
            shape, spec = shapes[name]
            base = spec if spec is not None else P(*([None] * len(shape)))
            specs.append(mesh_mod.sanitize_spec(P(PIPE_AXIS, *base), mesh))

        pipe_deg = int(mesh.shape[PIPE_AXIS])
        stage_policies = _resolve_policies(cfg, cfg.num_layers // pipe_deg)

        def fn(xv, *stacked):
            def stage(params_local, mb):
                def apply_full(carry, layer_slices):
                    d = dict(zip(_BLOCK_PARAMS, layer_slices))
                    return _block_apply_manual(d, carry, cfg=cfg, mesh=mesh)

                return _scan_policied(apply_full, tuple(params_local), mb,
                                      stage_policies)

            return pipeline_spmd(
                stage, stacked, xv, mesh=mesh, param_specs=specs,
                microbatches=cfg.pp_microbatches or None)

        return call_op(fn, x, *[getattr(self, n) for n in _BLOCK_PARAMS],
                       op_name="gpt_pipeline_1f1b")


class GPTEmbeddings(Layer):
    """Vocab-parallel word embedding + learned position embedding."""

    def __init__(self, cfg: GPTConfig, rs: np.random.RandomState):
        super().__init__()
        dt = dtype_mod.convert_dtype(cfg.dtype)
        std = cfg.initializer_range
        self.word_embeddings = _as_parameter(
            Tensor((rs.randn(cfg.vocab_size, cfg.hidden_size) * std
                    ).astype("float32"), dtype=dt),
            P(MODEL_AXIS, None))
        self.position_embeddings = _as_parameter(
            Tensor((rs.randn(cfg.max_position_embeddings, cfg.hidden_size) * std
                    ).astype("float32"), dtype=dt),
            None)
        self.dropout = Dropout(cfg.dropout)
        self.cfg = cfg

    def forward(self, input_ids, position_ids=None):
        def fn(w, pos, ids):
            emb = jnp.take(w, ids, axis=0)
            s = ids.shape[-1]
            pe = jax.lax.dynamic_slice_in_dim(pos, 0, s, axis=0)
            return emb + pe

        if position_ids is None:
            x = call_op(fn, self.word_embeddings, self.position_embeddings,
                        input_ids, op_name="gpt_embed")
        else:
            x = call_op(
                lambda w, pos, ids, pid: jnp.take(w, ids, 0) + jnp.take(pos, pid, 0),
                self.word_embeddings, self.position_embeddings, input_ids,
                position_ids, op_name="gpt_embed")
        x = mesh_mod.constrain(x, BATCH_AXES, SEQ_AXIS, None)
        return self.dropout(x)


class GPTModel(Layer):
    """Embeddings → L blocks → final LN. Returns hidden states [b, s, H]."""

    def __init__(self, config: GPTConfig, seed: int = 0):
        super().__init__()
        self.config = config
        rs = np.random.RandomState(seed)
        self.embeddings = GPTEmbeddings(config, rs)
        if config.mode == "scan":
            self.decoder = GPTScanDecoder(config, rs)
        else:
            from ..nn.layer.container import LayerList

            self.decoder = LayerList(
                [GPTDecoderLayer(config, rs) for _ in range(config.num_layers)])
        self.final_norm = LayerNorm(config.hidden_size,
                                    epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        if self.config.mode == "scan":
            x = self.decoder(x)
        else:
            for blk in self.decoder:
                x = blk(x)
        return self.final_norm(x)


class GPTForCausalLM(Layer):
    """LM head tied to the vocab-parallel embedding: logits stay vocab-sharded
    into the loss (reference: c_softmax_with_cross_entropy)."""

    def __init__(self, config: GPTConfig, seed: int = 0):
        super().__init__()
        self.gpt = GPTModel(config, seed=seed)
        self.config = config

    def forward(self, input_ids, position_ids=None, labels=None):
        x = self.gpt(input_ids, position_ids)
        w = self.gpt.embeddings.word_embeddings
        if labels is not None and self.config.fused_loss_chunk > 0:
            # fused chunked linear+CE: logits never hit HBM whole
            from ..incubate.nn.functional import fused_linear_cross_entropy

            h = x.reshape([-1, self.config.hidden_size])
            return fused_linear_cross_entropy(
                h, w, labels.reshape([-1]),
                vocab_chunk=self.config.fused_loss_chunk,
                transposed_weight=True)
        logits = call_op(lambda h, wv: h @ wv.T, x, w, op_name="gpt_logits")
        return mesh_mod.constrain(logits, BATCH_AXES, SEQ_AXIS, MODEL_AXIS)


class GPTPretrainingCriterion(Layer):
    """Masked LM loss over vocab-sharded logits (stable log-softmax in fp32)."""

    def forward(self, prediction_scores, masked_lm_labels, loss_mask=None):
        def fn(logits, labels, *mask):
            lg = logits.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, labels[..., None], axis=-1)[..., 0]
            nll = lse - picked
            if mask:
                m = mask[0].astype(jnp.float32)
                return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)
            return jnp.mean(nll)

        args = [prediction_scores, masked_lm_labels]
        if loss_mask is not None:
            args.append(loss_mask)
        return call_op(fn, *args, op_name="gpt_loss")


def gpt_1f1b_grad_fn(model: "GPTForCausalLM", *, memory_plan=None,
                     zero3_stage_params: bool = False, grad_sync=None,
                     sync_axes=(), sync_state_specs=()):
    """TrainStep grad_fn running the whole GPT train step under the
    memory-bounded 1F1B schedule (distributed/pipeline/schedule.py
    pipeline_1f1b; reference: pipeline_parallel.py:80-150
    forward_backward_pipeline).

    The embedding runs on stage 0, the decoder stack is pipe-stacked, and
    the final-norm + tied vocab-parallel LM head + CE run on the last stage
    — all inside ONE shard_map program; the tied embedding weight picks up
    both its stage-0 and last-stage grad contributions via the cross-stage
    psum. Requires cfg.mode == "scan", dropout 0 (no per-tick RNG plumbed).

    ISSUE-15 composition knobs (PipelineTrainStep drives these):

    - ``memory_plan`` (a ``distributed.pipeline.MemoryPlan``): per-layer
      remat/offload policies for the stage stack (overrides
      cfg.recompute/recompute_policy) + the stash's host-offload tier.
    - ``zero3_stage_params``: hold the pipe-stacked block weights at rest
      sharded over ('pipe', 'sharding') jointly on the layer dim — each
      rank keeps L/(P*Z) layers; the stage body all_gathers its own
      stage's slice over 'sharding' before scanning, and the gather's AD
      transpose (psum_scatter) both sums the sharding-batch-shard grad
      contributions AND re-shards the result: the ZeRO-3 x pipeline grad
      path, with fp32 grad accumulators and optimizer slots staying
      1/(P*Z)-sized (the PR-9 follow-on composition).
    - ``grad_sync`` / ``sync_axes`` / ``sync_state_specs``: the in-body
      quantized bucket-reduction hook forwarded to ``pipeline_1f1b`` —
      the grad_fn then takes and returns the residual state, one
      spec-sharded array per bucket (``handles_grad_comm`` marks the
      wider signature for TrainStep).
    """
    cfg = model.config
    if cfg.mode != "scan":
        raise ValueError("1F1B needs the scan-mode (pipe-stacked) decoder")
    if cfg.dropout or cfg.attn_dropout:
        raise ValueError(
            "the 1F1B schedule plumbs no per-tick RNG; set dropout=0 "
            "and attn_dropout=0 (the hybrid-parallel pretraining configs "
            "train without dropout)")
    mesh = mesh_mod.get_mesh()
    if mesh is None or PIPE_AXIS not in mesh.axis_names \
            or mesh.shape[PIPE_AXIS] <= 1:
        raise ValueError("1F1B needs an active mesh with pipe degree > 1")
    mp = int(mesh.shape.get(MODEL_AXIS, 1)) if MODEL_AXIS in mesh.axis_names else 1
    sep = int(mesh.shape.get(SEQ_AXIS, 1)) if SEQ_AXIS in mesh.axis_names else 1
    dt = dtype_mod.convert_dtype(cfg.dtype)
    eps = cfg.layer_norm_epsilon

    # FunctionalModule order -> short names (trainable params only)
    short = {"gpt.embeddings.word_embeddings": "wte",
             "gpt.embeddings.position_embeddings": "wpe",
             "gpt.final_norm.weight": "lnf_w",
             "gpt.final_norm.bias": "lnf_b"}
    for n in _BLOCK_PARAMS:
        short[f"gpt.decoder.{n}"] = n
    order = []
    for name, p in model.named_parameters():
        if p.stop_gradient:
            continue
        if name not in short:
            raise ValueError(f"unexpected GPT parameter {name}")
        order.append(short[name])

    shapes = _block_shapes(cfg)
    pipe_deg = int(mesh.shape[PIPE_AXIS])
    if cfg.num_layers % pipe_deg:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by pipe degree "
            f"{pipe_deg}")
    layers_per_stage = cfg.num_layers // pipe_deg
    shard_deg = (int(mesh.shape["sharding"])
                 if "sharding" in mesh.axis_names else 1)
    zero3 = bool(zero3_stage_params) and shard_deg > 1
    if zero3 and layers_per_stage % shard_deg:
        raise ValueError(
            f"zero3_stage_params shards the {layers_per_stage} layers of "
            f"a stage over sharding degree {shard_deg} — not divisible")
    specs = {"wte": mesh_mod.sanitize_spec(P(MODEL_AXIS, None), mesh),
             "wpe": P(), "lnf_w": P(), "lnf_b": P()}
    for n in _BLOCK_PARAMS:
        _, spec = shapes[n]
        base = spec if spec is not None else P(*([None] * len(shapes[n][0])))
        # at rest: layer dim over 'pipe' (one stage per pipe group), and
        # with zero3 additionally over 'sharding' (each rank keeps
        # L/(P*Z) layers; the stage body gathers its own stage's slice)
        lead = (PIPE_AXIS, "sharding") if zero3 else PIPE_AXIS
        specs[n] = mesh_mod.sanitize_spec(P(lead, *base), mesh)

    if memory_plan is not None:
        stage_policies = tuple(memory_plan.policies)
        if len(stage_policies) != layers_per_stage:
            raise ValueError(
                f"memory plan has {len(stage_policies)} per-layer policies "
                f"for a {layers_per_stage}-layer stage")
        stash_kind = memory_plan.stash_memory_kind
    else:
        stage_policies = _resolve_policies(cfg, layers_per_stage)
        stash_kind = None

    def embed_fn(p, ids):
        wte = p["wte"]
        if mp > 1:
            r = jax.lax.axis_index(MODEL_AXIS)
            vloc = wte.shape[0]
            off = r * vloc
            loc = jnp.clip(ids - off, 0, vloc - 1)
            emb = jnp.take(wte, loc, axis=0)
            emb = jnp.where(((ids >= off) & (ids < off + vloc))[..., None],
                            emb, 0)
            emb = coll.in_trace_psum(emb, MODEL_AXIS)   # c_embedding allreduce
        else:
            emb = jnp.take(wte, ids, axis=0)
        s_loc = ids.shape[1]
        pos0 = jax.lax.axis_index(SEQ_AXIS) * s_loc if sep > 1 else 0
        pe = jax.lax.dynamic_slice_in_dim(p["wpe"], pos0, s_loc, axis=0)
        return (emb + pe).astype(dt)

    def stage_fn(p, h):
        stacked = tuple(p[n] for n in _BLOCK_PARAMS)
        if zero3:
            # re-materialize this stage's L/P layers from the at-rest
            # 1/(P*Z) shards; AD's transpose (psum_scatter over
            # 'sharding') returns grads already summed over the sharding
            # batch shards AND sharded back to the at-rest layout
            stacked = tuple(
                coll.in_trace_all_gather(a, "sharding", gather_axis=0)
                for a in stacked)

        def apply_full(carry, slices):
            d = dict(zip(_BLOCK_PARAMS, slices))
            return _block_apply_manual(d, carry, cfg=cfg, mesh=mesh)

        return _scan_policied(apply_full, stacked, h, stage_policies)

    def loss_fn(p, y, lbl):
        x32 = y.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        ln = (x32 - mu) * jax.lax.rsqrt(var + eps) * p["lnf_w"] + p["lnf_b"]
        h2 = ln.reshape(-1, cfg.hidden_size).astype(dt)
        wte = p["wte"]
        flat = lbl.reshape(-1)
        logits = (h2 @ wte.T).astype(jnp.float32)
        if mp > 1:
            # ParallelCrossEntropy over the vocab-sharded logits
            # (c_softmax_with_cross_entropy, mp_layers.py)
            r = jax.lax.axis_index(MODEL_AXIS)
            vloc = wte.shape[0]
            off = r * vloc
            # the max-shift cancels out of d(lse)/d(logits) exactly, so it
            # can (and must — pmax has no VJP) sit behind stop_gradient
            lmax = coll.in_trace_pmax(
                jax.lax.stop_gradient(jnp.max(logits, axis=-1)), MODEL_AXIS)
            sumexp = coll.in_trace_psum(
                jnp.sum(jnp.exp(logits - lmax[:, None]), axis=-1), MODEL_AXIS)
            lse = jnp.log(sumexp) + lmax
            in_rng = (flat >= off) & (flat < off + vloc)
            loc = jnp.clip(flat - off, 0, vloc - 1)
            # local gather of each label's logit (zero off-shard), summed
            # across the vocab shards — exactly one rank contributes per
            # token (this line used to self-reference `picked` before it
            # was bound; the pre-vma TP refusal kept it unreached)
            picked_loc = jnp.take_along_axis(logits, loc[:, None],
                                             axis=-1)[:, 0]
            picked = coll.in_trace_psum(
                jnp.where(in_rng, picked_loc, 0.0), MODEL_AXIS)
        else:
            lse = jax.nn.logsumexp(logits, axis=-1)
            picked = jnp.take_along_axis(logits, flat[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - picked)

    from ..distributed.pipeline.schedule import pipeline_1f1b

    inv_shard = np.float32(1.0 / shard_deg)

    def _run(train_p, in_vals, lbl_vals, state):
        if len(in_vals) != 1 or len(lbl_vals) != 1:
            raise ValueError(
                "gpt 1F1B step takes exactly (input_ids,) and (labels,): "
                "custom position_ids / loss_mask are not plumbed through "
                "the pipeline schedule")
        p = dict(zip(order, train_p))
        out = pipeline_1f1b(
            embed_fn, stage_fn, loss_fn, p, in_vals[0], lbl_vals[0],
            mesh=mesh, param_specs={k: specs[k] for k in p},
            microbatches=cfg.pp_microbatches or None,
            natural_axes=(MODEL_AXIS,),
            grad_sync=grad_sync, sync_axes=sync_axes,
            sync_state=state, sync_state_specs=tuple(sync_state_specs),
            stash_memory_kind=stash_kind)
        if grad_sync is not None:
            loss, g, new_state = out
        else:
            (loss, g), new_state = out, ()
        if zero3:
            # the all_gather transpose SUMMED the sharding ranks' batch
            # contributions (psum_scatter); the unsharded semantics are
            # the mean over batch shards — scale once, linear either side
            # of the codec reduction
            g = {k: (v * inv_shard if k in _BLOCK_PARAMS else v)
                 for k, v in g.items()}
        return loss, [g[k] for k in order], tuple(new_state)

    if grad_sync is not None:
        def grad_fn(train_p, frozen_p, bvals, gc_res, key, in_vals,
                    lbl_vals):
            loss, grads, new_state = _run(train_p, in_vals, lbl_vals,
                                          tuple(gc_res))
            return loss, grads, new_state

        grad_fn.handles_grad_comm = True
    else:
        def grad_fn(train_p, frozen_p, bvals, key, in_vals, lbl_vals):
            loss, grads, _ = _run(train_p, in_vals, lbl_vals, ())
            return loss, grads

        grad_fn.handles_grad_comm = False
    # surfaced for PipelineTrainStep: the traversal order and at-rest
    # specs it builds its (local-shape) bucket plan and shardings from
    grad_fn.order = list(order)
    grad_fn.specs = dict(specs)
    grad_fn.zero3_stage_params = zero3
    grad_fn.stage_policies = tuple(stage_policies)
    return grad_fn


def gpt_1f1b_train_step(model: "GPTForCausalLM", optimizer, batch_spec=None,
                        **kwargs):
    """TrainStep whose loss+grads run the 1F1B pipeline schedule (the
    schedule_mode="1F1B" the reference's strategy selects); optimizer
    update, clipping and shardings are the standard compiled path.
    Extra kwargs (memory_plan=, zero3_stage_params=) forward to
    gpt_1f1b_grad_fn; for the grad_comm / planner-driven composition use
    distributed.pipeline.PipelineTrainStep, which builds on this."""
    from ..jit import TrainStep

    return TrainStep(model, None, optimizer, batch_spec=batch_spec,
                     grad_fn=gpt_1f1b_grad_fn(model, **kwargs))


def gpt_hbm_estimate(cfg: GPTConfig, mesh, global_batch: int,
                     seq: Optional[int] = None):
    """Per-device HBM estimate for one GSPMD AdamW train step — the
    BASELINE config-4 feasibility check (GPT-1.3B, ZeRO stage-2 sharding +
    mp2 on a v5e-64 mesh, per-chip HBM <= 16 GB).

    Compiles ABSTRACTLY (jax.ShapeDtypeStruct — no arrays materialized):
    embeddings -> scan-stacked decoder (remat honored via cfg.recompute) ->
    tied LM head + CE -> grads -> AdamW update with fp32 moments sharded
    over the 'sharding' axis (ZeRO stage-2: optimizer state sharded, bf16
    params replicated over 'sharding'). Params/moments are donated, so
    XLA's estimate is the real steady-state residency.

    Returns a dict of byte counts from XLA's memory analysis, including
    "peak_hbm_bytes" = arguments + temps + outputs - aliased.
    """
    import jax
    from jax.sharding import NamedSharding

    SDS = jax.ShapeDtypeStruct
    h, L = cfg.hidden_size, cfg.num_layers
    seq = seq or cfg.max_position_embeddings
    dt = dtype_mod.convert_dtype(cfg.dtype)
    shard_deg = (int(mesh.shape["sharding"])
                 if "sharding" in mesh.axis_names else 1)

    shapes = _block_shapes(cfg)
    pshapes = {"wte": (cfg.vocab_size, h),
               "wpe": (cfg.max_position_embeddings, h),
               "lnf_w": (h,), "lnf_b": (h,)}
    pspecs = {"wte": P(MODEL_AXIS, None), "wpe": P(),
              "lnf_w": P(), "lnf_b": P()}
    for n, (shape, spec) in shapes.items():
        base = tuple(spec) if spec is not None else (None,) * len(shape)
        pshapes[n] = (L, *shape)
        pspecs[n] = P(None, *base)
    pspecs = {k: mesh_mod.sanitize_spec(v, mesh) for k, v in pspecs.items()}

    from ..distributed.sharding import zero_slot_spec

    def slot_spec(shape, pspec):
        # the SAME ZeRO rule TrainStep applies to its slots, so a sharding
        # regression there is caught by the feasibility test
        return zero_slot_spec(shape, pspec, "sharding", shard_deg)

    def ns(spec):
        return NamedSharding(mesh, spec)

    params = {k: SDS(pshapes[k], dt, sharding=ns(pspecs[k]))
              for k in pshapes}
    sspecs = {k: slot_spec(pshapes[k], pspecs[k]) for k in pshapes}
    m1 = {k: SDS(pshapes[k], jnp.float32, sharding=ns(sspecs[k]))
          for k in pshapes}
    m2 = dict(m1)
    bspec = mesh_mod.sanitize_spec(P(BATCH_AXES), mesh)
    ids_sds = SDS((global_batch, seq), jnp.int32, sharding=ns(bspec))
    lbl_sds = SDS((global_batch, seq), jnp.int32, sharding=ns(bspec))

    def constrain(v, *spec):
        return jax.lax.with_sharding_constraint(
            v, ns(mesh_mod.sanitize_spec(P(*spec), mesh)))

    def train_step(p, mom1, mom2, ids, labels, lr):
        def loss_of(pp):
            x = jnp.take(pp["wte"], ids, axis=0) \
                + jax.lax.dynamic_slice_in_dim(pp["wpe"], 0, seq, axis=0)
            x = constrain(x.astype(dt), BATCH_AXES, SEQ_AXIS, None)
            stacked = tuple(pp[n] for n in _BLOCK_PARAMS)

            def apply_full(carry, slices):
                # _block_apply reads the ambient mesh for its sharding
                # constraints — callers set_mesh(mesh) first
                d = dict(zip(_BLOCK_PARAMS, slices))
                return _block_apply(d, carry, cfg=cfg)

            x = _scan_policied(apply_full, stacked, x,
                               _resolve_policies(cfg, L))
            x32 = x.astype(jnp.float32)
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.var(x32, axis=-1, keepdims=True)
            x = ((x32 - mu) * jax.lax.rsqrt(var + cfg.layer_norm_epsilon)
                 * pp["lnf_w"] + pp["lnf_b"]).astype(dt)
            logits = constrain(x @ pp["wte"].T,
                               BATCH_AXES, SEQ_AXIS, MODEL_AXIS)
            lg = logits.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, labels[..., None],
                                         axis=-1)[..., 0]
            return jnp.mean(lse - picked)

        loss, g = jax.value_and_grad(loss_of)(p)
        b1, b2, eps, wd = 0.9, 0.95, 1e-8, 0.1
        new_p, new_m1, new_m2 = {}, {}, {}
        for k in p:
            gk = g[k].astype(jnp.float32)
            nm1 = constrain_to(b1 * mom1[k] + (1 - b1) * gk, sspecs[k])
            nm2 = constrain_to(b2 * mom2[k] + (1 - b2) * gk * gk, sspecs[k])
            upd = nm1 / (jnp.sqrt(nm2) + eps) + wd * p[k].astype(jnp.float32)
            new_p[k] = constrain_to(
                (p[k].astype(jnp.float32) - lr * upd).astype(dt), pspecs[k])
            new_m1[k], new_m2[k] = nm1, nm2
        return loss, new_p, new_m1, new_m2

    def constrain_to(v, spec):
        return jax.lax.with_sharding_constraint(v, ns(spec))

    # _block_apply's per-activation constraints read the ambient mesh —
    # pin it to the argument for the trace so callers can't get a silently
    # unconstrained (wrong) estimate
    prev_mesh = mesh_mod.get_mesh()
    mesh_mod.set_mesh(mesh)
    try:
        lowered = jax.jit(train_step, donate_argnums=(0, 1, 2)).lower(
            params, m1, m2, ids_sds, lbl_sds,
            SDS((), jnp.float32))
    finally:
        mesh_mod.set_mesh(prev_mesh)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    if mem is None:
        return None
    out = {
        "argument_bytes": int(mem.argument_size_in_bytes),
        "output_bytes": int(mem.output_size_in_bytes),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "alias_bytes": int(mem.alias_size_in_bytes),
    }
    out["peak_hbm_bytes"] = (out["argument_bytes"] + out["temp_bytes"]
                             + out["output_bytes"] - out["alias_bytes"])
    from ..jit.aot import cost_counters

    # raw compiler cost counters for the planner's ranking signal
    # (jit/aot.py estimate_step_seconds decides how to trust them:
    # optimal_seconds goes negative-sentinel on large collective
    # programs, flops/bytes stay valid)
    out.update(cost_counters(compiled))
    return out

"""paddle_tpu.nn (reference: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer  # noqa: F401
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, SELU, Sigmoid,
    Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh, Tanhshrink,
    ThresholdedReLU,
)
from .layer.common import (  # noqa: F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D, Dropout3D,
    Embedding, Flatten, Fold, Identity, Linear, Pad1D, Pad2D, Pad3D,
    PixelShuffle, Unfold, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D,
    ZeroPad2D,
    ChannelShuffle, HSigmoidLoss, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    PairwiseDistance, PixelUnshuffle,
)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer.rnn import (  # noqa: F401
    GRU, GRUCell, LSTM, LSTMCell, RNN, BiRNN, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layer.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    HingeEmbeddingLoss, KLDivLoss, L1Loss, MarginRankingLoss, MSELoss, NLLLoss,
    SmoothL1Loss, TripletMarginLoss,
    CTCLoss, GaussianNLLLoss, MultiMarginLoss, PoissonNLLLoss,
    SoftMarginLoss, AdaptiveLogSoftmaxWithLoss,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm1D,
    InstanceNorm2D, InstanceNorm3D, LayerNorm, LocalResponseNorm, SpectralNorm,
    SyncBatchNorm,
)
from .layer.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    MaxPool1D, MaxPool2D, MaxPool3D,
)

from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)

from ..framework.param_attr import ParamAttr  # noqa: F401
from ..framework.tensor import Parameter  # noqa: F401


class ClipGradByGlobalNorm:
    """Gradient clipping by global norm (reference: fluid/clip.py
    GradientClipByGlobalNorm). Consumed by Optimizer.step."""

    def __init__(self, clip_norm=1.0, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __repr__(self):
        return f"ClipGradByGlobalNorm(clip_norm={self.clip_norm})"


class ClipGradByNorm:
    def __init__(self, clip_norm=1.0):
        self.clip_norm = float(clip_norm)


class ClipGradByValue:
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max
from . import utils  # noqa: F401,E402

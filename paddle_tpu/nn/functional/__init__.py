"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional/__init__.py)."""
from .activation import (  # noqa: F401
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, relu_, selu, sigmoid, silu, softmax, softmax_, softplus, softshrink,
    softsign, swish, tanh, tanhshrink, thresholded_relu,
)
from .common import (  # noqa: F401
    alpha_dropout, bilinear, cosine_similarity, dropout, dropout2d, dropout3d,
    embedding, fold, interpolate, label_smooth, linear, normalize, one_hot, pad,
    pixel_shuffle, unfold, upsample, zeropad2d,
    affine_grid, channel_shuffle, grid_sample, max_unpool2d,
    pairwise_distance, pixel_unshuffle, temporal_shift,
)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d, conv3d_transpose,
)
from .loss import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits, cosine_embedding_loss,
    cross_entropy, ctc_loss, hinge_embedding_loss, kl_div, l1_loss, log_loss,
    margin_ranking_loss, mse_loss, nll_loss, sigmoid_focal_loss, smooth_l1_loss,
    softmax_with_cross_entropy, square_error_cost, triplet_margin_loss,
    gaussian_nll_loss, multi_margin_loss, poisson_nll_loss,
    soft_margin_loss,
)
from .attention import scaled_dot_product_attention  # noqa: F401
from .flash_attention import flash_attention, flash_attn_unpadded  # noqa: F401
from .sequence import (  # noqa: F401
    sequence_concat, sequence_expand, sequence_first_step, sequence_last_step,
    sequence_mask, sequence_pad, sequence_pool, sequence_reverse,
    sequence_slice, sequence_softmax, sequence_unpad,
)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d, avg_pool1d,
    avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
)

"""paddle_tpu.nn.functional (reference: python/paddle/nn/functional/__init__.py)."""
from .activation import (  # noqa: F401
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, relu_, selu, sigmoid, silu, softmax, softmax_, softplus, softshrink,
    softsign, swish, tanh, tanhshrink, thresholded_relu,
)
from .common import (  # noqa: F401
    alpha_dropout, bilinear, cosine_similarity, dropout, dropout2d, dropout3d,
    embedding, fold, interpolate, label_smooth, linear, normalize, one_hot, pad,
    pixel_shuffle, unfold, upsample, zeropad2d,
    affine_grid, channel_shuffle, grid_sample, max_unpool2d,
    pairwise_distance, pixel_unshuffle, temporal_shift,
)
from .conv import (  # noqa: F401
    conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d, conv3d_transpose,
)
from .loss import (  # noqa: F401
    binary_cross_entropy, binary_cross_entropy_with_logits, cosine_embedding_loss,
    cross_entropy, ctc_loss, hinge_embedding_loss, kl_div, l1_loss, log_loss,
    margin_ranking_loss, mse_loss, nll_loss, sigmoid_focal_loss, smooth_l1_loss,
    softmax_with_cross_entropy, square_error_cost, triplet_margin_loss,
    gaussian_nll_loss, multi_margin_loss, poisson_nll_loss,
    soft_margin_loss,
)
from .attention import scaled_dot_product_attention  # noqa: F401
from .flash_attention import flash_attention, flash_attn_unpadded  # noqa: F401
from .sequence import (  # noqa: F401
    sequence_concat, sequence_expand, sequence_first_step, sequence_last_step,
    sequence_mask, sequence_pad, sequence_pool, sequence_reverse,
    sequence_slice, sequence_softmax, sequence_unpad,
)
from .norm import (  # noqa: F401
    batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d, avg_pool1d,
    avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
)

# reference-parity tail
from ...tensor.math import tanh_  # noqa: F401,E402
from .common import (  # noqa: F401,E402
    affine_channel, batch_fc, bilateral_slice, conv_shift, correlation,
    cvm, diag_embed, filter_by_instag, fsp_matrix, gather_tree, im2sequence,
    inplace_abn, max_unpool1d, max_unpool3d, rank_attention, tree_conv,
)
from .loss import (  # noqa: F401,E402
    bpr_loss, center_loss, class_center_sample, dice_loss, hsigmoid_loss,
    margin_cross_entropy, npair_loss, rank_loss,
)


def elu_(x, alpha=1.0, name=None):
    """Inplace elu (reference: elu_ inplace variant)."""
    from .activation import elu

    x._replace_from(elu(x, alpha))
    return x


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention over a CSR sparsity pattern (reference:
    sparse_attention_op.cu). Each query row attends only to the keys listed
    in its CSR row; softmax runs over just those entries.

    CSR offsets/columns: [B, H, L+1] / [B, H, nnz] int32 (the reference's
    layout). Dense fallback implementation — rows gather their permitted
    keys, so memory is O(nnz·d), not O(L²)."""
    import jax
    import jax.numpy as jnp

    from ...framework.autograd import call_op

    def fn(q, k, v, offs, cols):
        b, h, L, d = q.shape
        nnz = cols.shape[-1]
        # per-entry row index from CSR offsets
        pos = jnp.arange(nnz)
        row_of = (pos[None, None, :] >=
                  offs[..., 1:, None]).sum(-2)          # [B,H,nnz]
        scale = 1.0 / jnp.sqrt(d)
        bi = jnp.arange(b)[:, None, None]
        hi = jnp.arange(h)[None, :, None]
        qk = jnp.einsum("bhnd,bhnd->bhn",
                        q[bi, hi, row_of], k[bi, hi, cols]) * scale
        # segment softmax over each row's entries
        row_max = jnp.full((b, h, L), -1e30)
        row_max = row_max.at[bi, hi, row_of].max(qk)
        e = jnp.exp(qk - row_max[bi, hi, row_of])
        denom = jnp.zeros((b, h, L)).at[bi, hi, row_of].add(e)
        w = e / jnp.maximum(denom[bi, hi, row_of], 1e-30)
        out = jnp.zeros_like(q)
        out = out.at[bi, hi, row_of].add(w[..., None] * v[bi, hi, cols])
        return out

    return call_op(fn, query, key, value, sparse_csr_offset,
                   sparse_csr_columns, op_name="sparse_attention")

"""Common functionals: linear, dropout, embedding, pad, interpolate, one_hot...

Reference: python/paddle/nn/functional/common.py, input.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.ndimage import map_coordinates

from ...framework.autograd import call_op as op, is_grad_enabled
from ...framework.random import next_key
from ...framework.tensor import Tensor


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle weight layout [in_features, out_features]
    (reference: nn/functional/common.py linear; matmul_v2 + elementwise_add).
    Maps straight onto the MXU via dot_general."""
    if bias is None:
        return op(lambda v, w: jnp.matmul(v, w), x, weight, op_name="linear")
    return op(lambda v, w, b: jnp.matmul(v, w) + b, x, weight, bias, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    """Reference: operators/dropout_op.* — upscale_in_train divides keep_prob
    out at train time; downscale_in_infer scales at eval."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and training is False and p > 0:
            return op(lambda v: v * (1.0 - p), x, op_name="dropout_infer")
        return x.clone() if isinstance(x, Tensor) else x
    if p == 1.0:
        return op(jnp.zeros_like, x, op_name="dropout")
    key = next_key()

    def fn(v):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return op(fn, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x.clone()
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p**2))).astype(np.float32)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return op(fn, x, op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: operators/lookup_table_v2_op.*; vocab gather on TPU.

    sparse=True: the weight gradient comes back as SelectedRows (rows =
    looked-up ids, values = output cotangents) — O(batch·seq·dim), never
    O(vocab·dim) (reference: lookup_table grad with is_sparse, applied by
    the lazy-mode sparse optimizer kernels)."""
    if sparse:
        from ...framework import autograd as ag
        from ...framework.selected_rows import SelectedRows
        from ...framework.tensor import Tensor

        ids_val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        w_val = weight._value
        out_val = jnp.take(w_val, ids_val, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            out_val = jnp.where((ids_val == padding_idx)[..., None], 0.0,
                                out_val)
        out = Tensor(out_val, _internal=True)
        if ag.is_grad_enabled() and not weight.stop_gradient:
            V, dim = w_val.shape

            def vjp_fn(cot):
                rows = ids_val.reshape(-1).astype(jnp.int32)
                c = cot.reshape(-1, dim).astype(w_val.dtype)
                if padding_idx is not None and padding_idx >= 0:
                    c = jnp.where((rows == padding_idx)[:, None], 0.0, c)
                return [SelectedRows(rows, c, V)]

            node = ag.GradNode(
                vjp_fn, [(weight, weight._grad_node, weight._out_index)],
                [jax.ShapeDtypeStruct(out_val.shape, out_val.dtype)],
                multi_output=False, name="embedding_sparse")
            out.stop_gradient = False
            out._grad_node = node
            out._out_index = 0
        return out

    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return op(fn, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    return op(lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32), x, op_name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(lbl, *rest):
        k = lbl.shape[-1]
        if rest:
            return (1 - epsilon) * lbl + epsilon * rest[0]
        return (1 - epsilon) * lbl + epsilon / k

    if prior_dist is not None:
        return op(fn, label, prior_dist, op_name="label_smooth")
    return op(fn, label, op_name="label_smooth")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy()]
    pad = [int(p) for p in pad]

    def fn(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad applies to last len(pad)//2 spatial dims,
            # ordered from the last dim backwards: [left, right, top, bottom, ...]
            cfg = [(0, 0)] * nd
            npairs = len(pad) // 2
            if data_format.startswith("NC"):
                dims = list(range(nd - 1, nd - 1 - npairs, -1))
            else:
                dims = list(range(nd - 2, nd - 2 - npairs, -1))
            for i, d in enumerate(dims):
                cfg[d] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, cfg, mode="constant", constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)

    return op(fn, x, op_name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        nrm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(nrm, epsilon)

    return op(fn, x, op_name="normalize")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)

    return op(fn, x1, x2, op_name="cosine_similarity")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    """Reference: operators/interpolate_v2_op.*. The 2-D nearest/bilinear
    cases use the reference-exact sampling (incl. align_corners and
    align_mode — shared with the artifact importer, interop/importer.py
    _interp_2d); other modes fall back to jax.image.resize."""
    if isinstance(size, Tensor):
        size = [int(s) for s in size.numpy()]

    def fn(v):
        if data_format == "NCHW":
            spatial = list(v.shape[2:])
        else:
            spatial = list(v.shape[1:-1])
        if size is not None:
            out_sp = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            out_sp = [int(s * f) for s, f in zip(spatial, sf)]
        if mode in ("nearest", "bilinear") and len(out_sp) == 2:
            from ...interop.importer import _interp_2d

            vv = v if data_format == "NCHW" else jnp.moveaxis(v, -1, 1)
            out = _interp_2d(jnp, vv, out_sp[0], out_sp[1],
                             bilinear=(mode == "bilinear"),
                             align_corners=bool(align_corners),
                             align_mode=int(align_mode))
            return out if data_format == "NCHW" else jnp.moveaxis(out, 1, -1)
        m = {"nearest": "nearest", "bilinear": "bilinear", "trilinear": "trilinear",
             "bicubic": "bicubic", "linear": "linear", "area": "linear"}[mode]
        if data_format == "NCHW":
            out_shape = list(v.shape[:2]) + out_sp
        else:
            out_shape = [v.shape[0]] + out_sp + [v.shape[-1]]
        return jax.image.resize(v, tuple(out_shape), method=m)

    return op(fn, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/unfold_op.*, math/im2col.cc)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        oh = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                patch = v[:, :, di : di + oh * st[0] : st[0], dj : dj + ow * st[1] : st[1]]
                patches.append(patch)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return op(fn, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        ph, pw = os_[0] + pd[0] + pd[2], os_[1] + pd[1] + pd[3]
        oh = (ph - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, ph, pw), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                di, dj = i * dl[0], j * dl[1]
                out = out.at[:, :, di : di + oh * st[0] : st[0], dj : dj + ow * st[1] : st[1]].add(
                    v[:, :, i, j]
                )
        return out[:, :, pd[0] : pd[0] + os_[0], pd[1] : pd[1] + os_[1]]

    return op(fn, x, op_name="fold")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    if bias is not None:
        return op(fn, x1, x2, weight, bias, op_name="bilinear")
    return op(fn, x1, x2, weight, op_name="bilinear")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return op(fn, x, op_name="pixel_shuffle")


# ---------------------------------------------------------------------------
# functional tail: grid_sample/affine_grid, shuffles, unpool, losses
# (reference: operators/grid_sampler_op, affine_grid_op, pixel ops, losses)
# ---------------------------------------------------------------------------

def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            return v.transpose(0, 1, 3, 5, 2, 4).reshape(
                n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        return v.transpose(0, 1, 3, 2, 4, 5).reshape(
            n, h // r, w // r, c * r * r)

    return op(fn, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            return v.reshape(n, g, c // g, h, w).transpose(
                0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = v.shape
        return v.reshape(n, h, w, g, c // g).transpose(
            0, 1, 2, 4, 3).reshape(n, h, w, c)

    return op(fn, x, op_name="channel_shuffle")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """[n, 2, 3] affine params → [n, H, W, 2] sampling grid
    (affine_grid_op)."""
    def fn(th):
        n, _, h, w = [int(s) for s in (out_shape if not hasattr(
            out_shape, "numpy") else out_shape.numpy())]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
        return jnp.einsum("hwk,nck->nhwc", base, th)

    return op(fn, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x[NCHW] at grid[N,H,W,2] (x,y in [-1,1]) — grid_sampler_op."""
    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def gather(yy, xx):
            ob = (yy < 0) | (yy > h - 1) | (xx < 0) | (xx > w - 1)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            out = v[jnp.arange(n)[:, None, None], :, yc, xc]  # [n,H,W,c]
            if padding_mode == "zeros":
                out = jnp.where(ob[..., None], 0.0, out)
            return out

        if mode == "nearest":
            res = gather(jnp.round(fy), jnp.round(fx))
        else:
            y0, x0 = jnp.floor(fy), jnp.floor(fx)
            wy, wx = fy - y0, fx - x0
            res = (gather(y0, x0) * ((1 - wy) * (1 - wx))[..., None]
                   + gather(y0, x0 + 1) * ((1 - wy) * wx)[..., None]
                   + gather(y0 + 1, x0) * (wy * (1 - wx))[..., None]
                   + gather(y0 + 1, x0 + 1) * (wy * wx)[..., None])
        return jnp.transpose(res, (0, 3, 1, 2))

    return op(fn, x, grid, op_name="grid_sample")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Scatter pooled values back to their argmax positions
    (unpool_op)."""
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
        (kernel_size, kernel_size)
    st = stride or ks
    st = st if isinstance(st, (list, tuple)) else (st, st)

    def fn(v, idx):
        n, c, h, w = v.shape
        pd = padding if isinstance(padding, (list, tuple)) else (padding,) * 2
        if output_size is not None:
            oh, ow = [int(s) for s in output_size[-2:]]
        else:
            # reference unpool_op: (L-1)*stride + kernel - 2*padding
            oh = (h - 1) * st[0] + ks[0] - 2 * int(pd[0])
            ow = (w - 1) * st[1] + ks[1] - 2 * int(pd[1])
        flat = jnp.zeros((n, c, oh * ow), v.dtype)
        iidx = idx.reshape(n, c, -1).astype(jnp.int32)
        flat = flat.at[jnp.arange(n)[:, None, None],
                       jnp.arange(c)[None, :, None], iidx].set(
            v.reshape(n, c, -1))
        return flat.reshape(n, c, oh, ow)

    return op(fn, x, indices, op_name="max_unpool2d")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM shift (temporal_shift_op): shift C/4 channels fwd/back in time."""
    def fn(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :fold], jnp.zeros_like(
            v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, fold:2 * fold]),
                               v[:, :-1, fold:2 * fold]], axis=1)
        rest = v[:, :, 2 * fold:]
        return jnp.concatenate([back, fwd, rest], axis=2).reshape(
            nt, c, h, w)

    return op(fn, x, op_name="temporal_shift")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = jnp.abs(a - b) + epsilon
        return jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return op(fn, x, y, op_name="pairwise_distance")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    """1-D unpool: scatter pooled values to their argmax positions
    (reference: unpool_op 1-D variant)."""
    ks = kernel_size if not isinstance(kernel_size, (list, tuple)) else \
        kernel_size[0]
    st = stride or ks
    st = st if not isinstance(st, (list, tuple)) else st[0]

    def fn(v, idx):
        n, c, l = v.shape
        # reference unpool_op: (L-1)*stride + kernel - 2*padding
        ol = (int(output_size[-1]) if output_size is not None
              else (l - 1) * int(st) + int(ks) - 2 * int(padding))
        flat = jnp.zeros((n, c, ol), v.dtype)
        iidx = idx.reshape(n, c, -1).astype(jnp.int32)
        return flat.at[jnp.arange(n)[:, None, None],
                       jnp.arange(c)[None, :, None], iidx].set(
            v.reshape(n, c, -1))

    return op(fn, x, indices, op_name="max_unpool1d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """3-D unpool (reference: unpool_op 3-D variant); indices flatten the
    output D*H*W grid, matching max_pool3d(return_mask=True)."""
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
        (kernel_size,) * 3
    st = stride or ks
    st = st if isinstance(st, (list, tuple)) else (st,) * 3

    def fn(v, idx):
        n, c, d, h, w = v.shape
        pd = padding if isinstance(padding, (list, tuple)) else (padding,) * 3
        if output_size is not None:
            od, oh, ow = [int(s) for s in output_size[-3:]]
        else:
            od = (d - 1) * st[0] + ks[0] - 2 * int(pd[0])
            oh = (h - 1) * st[1] + ks[1] - 2 * int(pd[1])
            ow = (w - 1) * st[2] + ks[2] - 2 * int(pd[2])
        flat = jnp.zeros((n, c, od * oh * ow), v.dtype)
        iidx = idx.reshape(n, c, -1).astype(jnp.int32)
        flat = flat.at[jnp.arange(n)[:, None, None],
                       jnp.arange(c)[None, :, None], iidx].set(
            v.reshape(n, c, -1))
        return flat.reshape(n, c, od, oh, ow)

    return op(fn, x, indices, op_name="max_unpool3d")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Embed the last dim as a diagonal of a new square matrix (reference:
    diag_embed_op.cc; matches torch.diag_embed semantics)."""
    def fn(v):
        n = v.shape[-1] + abs(int(offset))
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        r = jnp.arange(v.shape[-1])
        rows = r + max(-int(offset), 0)
        cols = r + max(int(offset), 0)
        out = base.at[..., rows, cols].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        order = []
        src = {d1: nd - 2, d2: nd - 1}
        it = iter(perm)
        for i in range(nd):
            order.append(src[i] if i in src else next(it))
        return jnp.transpose(out, order)

    return op(fn, input, op_name="diag_embed")


def gather_tree(ids, parents, name=None):
    """Back-trace full beam-search sequences from per-step ids and parent
    beam indices (reference: gather_tree_op.cc). ids/parents: [T, B, W]."""
    def fn(idv, par):
        T = idv.shape[0]

        def step(carry, t):
            beams = carry  # [B, W] beam index selected at step t+1
            b = jnp.arange(idv.shape[1])[:, None]
            out_t = idv[t][b, beams]
            prev = par[t][b, beams]
            return prev, out_t

        # walk T-1 .. 0, starting from identity beam order at the last step
        init = jnp.broadcast_to(jnp.arange(idv.shape[2]),
                                idv.shape[1:]).astype(par.dtype)
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return outs[::-1]

    return op(fn, ids, parents, op_name="gather_tree")


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None):
    """Per-channel affine y = scale*x + bias (reference:
    affine_channel_op.cc — frozen-BN folding in detection models)."""
    c_axis = 1 if data_layout == "NCHW" else -1

    def fn(v, s, b):
        shape = [1] * v.ndim
        shape[c_axis] = v.shape[c_axis]
        return v * s.reshape(shape) + b.reshape(shape)

    return op(fn, x, scale, bias, op_name="affine_channel")


def cvm(input, cvm_in, use_cvm=True, name=None):
    """Continuous-value model op for CTR features (reference: cvm_op.cc):
    each instance's leading 2 columns are (show, click) statistics; with
    use_cvm they are log-transformed in place, else stripped."""
    def fn(v, c):
        show = jnp.log(c[:, 0:1] + 1.0)
        click = jnp.log(c[:, 1:2] + 1.0) - jnp.log(c[:, 0:1] + 1.0)
        if use_cvm:
            return jnp.concatenate([show, click, v[:, 2:]], axis=1)
        return v[:, 2:]

    return op(fn, input, cvm_in, op_name="cvm")


def im2sequence(input, filter_size=1, stride=1, padding=0, out_stride=1,
                name=None):
    """Unfold image patches into sequence rows (reference:
    im2sequence_op.cc): [N, C, H, W] -> [N*out_h*out_w, C*kh*kw], row-major
    over (n, oh, ow) like the reference's LoD layout."""
    if out_stride != 1:
        raise ValueError(
            "im2sequence: out_stride (real-image-size mode) is not "
            "supported; pass pre-scaled inputs")
    ks = filter_size if isinstance(filter_size, (list, tuple)) else \
        (filter_size, filter_size)
    st = stride if isinstance(stride, (list, tuple)) else (stride, stride)
    if isinstance(padding, (list, tuple)) and len(padding) == 2:
        padding = (padding[0], padding[0], padding[1], padding[1])
    pd = padding if isinstance(padding, (list, tuple)) else \
        (padding,) * 4  # up, down, left, right

    def fn(v):
        n, c, h, w = v.shape
        vp = jnp.pad(v, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
        out_h = (vp.shape[2] - ks[0]) // st[0] + 1
        out_w = (vp.shape[3] - ks[1]) // st[1] + 1
        patches = jax.lax.conv_general_dilated_patches(
            vp, filter_shape=ks, window_strides=st, padding="VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # [N, C*kh*kw, out_h, out_w] -> rows (n, oh, ow)
        return patches.transpose(0, 2, 3, 1).reshape(
            n * out_h * out_w, c * ks[0] * ks[1])

    return op(fn, input, op_name="im2sequence")


def conv_shift(x, y, name=None):
    """Circular convolution/correlation (reference: conv_shift_op.cc):
    x [B, N], y [B, M] (M odd, M <= N); out[b, i] = sum_j x[b, (i + j -
    (M-1)/2) mod N] * y[b, j]."""
    def fn(xv, yv):
        B, N = xv.shape
        M = yv.shape[1]
        half = (M - 1) // 2
        idx = (jnp.arange(N)[:, None] + jnp.arange(M)[None, :] - half) % N
        gathered = xv[:, idx]                       # [B, N, M]
        return jnp.einsum("bnm,bm->bn", gathered, yv)

    return op(fn, x, y, op_name="conv_shift")


def fsp_matrix(x, y, name=None):
    """FSP (flow of solution procedure) matrix for distillation
    (reference: fsp_op.cc): [B, C1, H, W] x [B, C2, H, W] ->
    [B, C1, C2] = mean over H*W of outer products."""
    def fn(a, b):
        hw = a.shape[2] * a.shape[3]
        return jnp.einsum("bchw,bdhw->bcd", a, b) / hw

    return op(fn, x, y, op_name="fsp_matrix")


def batch_fc(input, w, bias=None, name=None):
    """Per-slot batched fc (reference: batch_fc_op.cc, CTR rank models):
    input [S, B, IN], w [S, IN, OUT], bias [S, OUT] -> [S, B, OUT]."""
    def fn(v, wv, *rest):
        out = jnp.einsum("sbi,sio->sbo", v, wv)
        if rest:
            out = out + rest[0][:, None, :]
        return out

    args = [input, w] + ([bias] if bias is not None else [])
    return op(fn, *args, op_name="batch_fc")


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """FlowNet-style correlation/cost volume (reference:
    correlation_op.cc): for each displacement (dy, dx) on the stride2 grid
    within max_displacement, the channel-mean of x1 · shifted(x2), patch-
    summed over kernel_size. Output [N, D*D, out_h, out_w] with
    D = 2*(max_displacement//stride2) + 1."""
    if kernel_size % 2 != 1:
        raise ValueError("correlation: kernel_size must be odd")
    kr = kernel_size // 2
    dr = max_displacement // stride2
    D = 2 * dr + 1

    def fn(a, b):
        n, c, h, w = a.shape
        pad = pad_size
        ap = jnp.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        border = kr + max_displacement
        out_h = (h + 2 * pad - 2 * border + stride1 - 1) // stride1
        out_w = (w + 2 * pad - 2 * border + stride1 - 1) // stride1
        ys = border + stride1 * jnp.arange(out_h)
        xs = border + stride1 * jnp.arange(out_w)
        maps = []
        for dy in range(-dr, dr + 1):
            for dx in range(-dr, dr + 1):
                oy, ox = dy * stride2, dx * stride2
                prod = ap * jnp.roll(bp, (-oy, -ox), axis=(2, 3))
                # patch sum over the kernel window, then channel mean
                win = jax.lax.reduce_window(
                    prod, 0.0, jax.lax.add,
                    (1, 1, kernel_size, kernel_size), (1, 1, 1, 1),
                    "SAME")
                m = jnp.mean(win, axis=1)                   # [N, H+2p, W+2p]
                maps.append(m[:, ys][:, :, xs])
        # reference normalizes by kernel_size^2 * C; channel mean is done
        return jnp.stack(maps, axis=1) / (kernel_size * kernel_size)

    out = op(fn, x1, x2, op_name="correlation")
    return out


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0, ins_lod=None, name=None):
    """Keep rows whose instance tags intersect filter_tag (reference:
    filter_by_instag_op.cc). The kept indices are decided host-side (the
    output size is data-dependent, like the reference's LoD output) but
    the rows are selected with a tape gather, so gradients scatter back to
    the kept rows of ``ins`` (reference filter_by_instag_grad).

    ``ins_lod``: per-instance row counts when an instance spans several
    rows of ``ins`` (the reference's LoD form); ins_tag is per-instance.
    Returns (filtered_rows, loss_weight, kept_row_index)."""
    from ...framework.tensor import Tensor, to_tensor

    def _np(v):
        return np.asarray(v.numpy() if isinstance(v, Tensor) else v)

    n_rows = int(ins.shape[0])
    tags = _np(ins_tag)
    keep_tags = set(_np(filter_tag).reshape(-1).tolist())
    if tags.ndim == 1:
        tags = tags.reshape(-1, 1)
    if ins_lod is not None:
        lens = [int(n) for n in _np(ins_lod).reshape(-1)]
        if sum(lens) != n_rows or len(lens) != tags.shape[0]:
            raise ValueError(
                f"ins_lod (sum {sum(lens)}, {len(lens)} instances) "
                f"inconsistent with ins rows {n_rows} / "
                f"{tags.shape[0]} tag rows")
    else:
        if tags.shape[0] != n_rows:
            raise ValueError(
                f"ins_tag has {tags.shape[0]} instances for {n_rows} rows; "
                "pass ins_lod when instances span multiple rows")
        lens = [1] * n_rows
    kept_rows = []
    offset = 0
    for inst, ln in enumerate(lens):
        if keep_tags & set(tags[inst].reshape(-1).tolist()):
            kept_rows.extend(range(offset, offset + ln))
        offset += ln
    if not kept_rows:
        out = np.full((1,) + tuple(int(d) for d in ins.shape[1:]),
                      out_val_if_empty,
                      _np(ins).dtype if not isinstance(ins, Tensor)
                      else np.dtype(str(np.asarray(ins.numpy()).dtype)))
        return (to_tensor(out), to_tensor(np.zeros((1, 1), np.float32)),
                to_tensor(np.zeros((0,), np.int64)))
    idx = np.asarray(kept_rows, np.int64)
    ins_t = ins if isinstance(ins, Tensor) else to_tensor(_np(ins))
    # tape gather: backward scatters cotangents onto the kept rows
    sel = op(lambda v, i: jnp.take(v, i, axis=0), ins_t, to_tensor(idx),
             op_name="filter_by_instag")
    return (sel, to_tensor(np.ones((len(kept_rows), 1), np.float32)),
            to_tensor(idx))


def inplace_abn(x, running_mean, running_var, weight=None, bias=None,
                training=False, momentum=0.9, epsilon=1e-5,
                activation="identity", alpha=0.01, data_format="NCHW",
                name=None):
    """In-place activated batch norm (reference: inplace_abn_op.cc): BN
    followed by identity/leaky_relu/elu. The 'in-place' memory trick is
    XLA's job (buffer reuse under jit); semantics = BN + activation."""
    from .norm import batch_norm

    out = batch_norm(x, running_mean, running_var, weight=weight, bias=bias,
                     training=training, momentum=momentum, epsilon=epsilon,
                     data_format=data_format)
    if activation in ("identity", None):
        return out
    if activation == "leaky_relu":
        from .activation import leaky_relu

        return leaky_relu(out, negative_slope=alpha)
    if activation == "elu":
        from .activation import elu

        return elu(out, alpha=alpha)
    raise ValueError(f"inplace_abn: unsupported activation {activation!r}")


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """HDRNet bilateral-grid slicing (reference: bilateral_slice_op.cu):
    the guide image picks a depth in the bilateral grid; trilinear-sampled
    per-pixel affine coefficients are applied to the input channels
    (+ per-channel offset when has_offset).

    x [N, C, H, W]; guide [N, H, W] in [0, 1]; grid
    [N, coeff_ch, gd, gh, gw] with coeff_ch = n_out*(C+1) (has_offset) or
    n_out*C. Output [N, n_out, H, W].
    """
    def fn(xv, gv, grid_v):
        N, C, H, W = xv.shape
        _, coeff_ch, gd, gh, gw = grid_v.shape
        stride = C + 1 if has_offset else C
        if coeff_ch % stride != 0:
            raise ValueError(
                f"bilateral_slice: grid channels {coeff_ch} not a multiple "
                f"of {'C+1' if has_offset else 'C'}={stride}")
        n_out = coeff_ch // stride
        # sample coordinates in grid index space (cell centers at i+0.5,
        # edge-clamped trilinear == map_coordinates order-1 'nearest')
        px = (jnp.arange(W) + 0.5) * gw / W - 0.5
        py = (jnp.arange(H) + 0.5) * gh / H - 0.5
        pz = gv * gd - 0.5                              # [N, H, W]
        zz = pz
        yy = jnp.broadcast_to(py[None, :, None], (N, H, W))
        xx = jnp.broadcast_to(px[None, None, :], (N, H, W))

        def sample_one(g_c, z, y, x_):
            return map_coordinates(g_c, [z, y, x_], order=1, mode="nearest")

        # [N, coeff_ch, H, W]: vmap channels then batch
        coeffs = jax.vmap(
            lambda g_n, z, y, x_: jax.vmap(
                lambda g_c: sample_one(g_c, z, y, x_))(g_n)
        )(grid_v, zz, yy, xx)
        coeffs = coeffs.reshape(N, n_out, stride, H, W)
        out = jnp.einsum("nochw,nchw->nohw", coeffs[:, :, :C], xv)
        if has_offset:
            out = out + coeffs[:, :, C]
        return out

    return op(fn, x, guide, grid, op_name="bilateral_slice")


def tree_conv(nodes_vector, edge_set, filter, max_depth=2, name=None):
    """Tree-based convolution (reference: tree_conv_op.cc + math/tree2col —
    TBCNN, Mou et al.): every node's patch is its subtree to depth
    max_depth; each patch node contributes its feature weighted by the
    continuous position weights (eta_l, eta_r, eta_t), and the collected
    patch contracts against the filter.

    The tree STRUCTURE is data (host-side DFS, like the reference's CPU
    tree2col); the contraction runs on the tape, so gradients flow to both
    nodes_vector and filter.

    nodes_vector [B, N, F]; edge_set [B, E, 2] (1-indexed parent/child,
    (0,0) padding); filter [F, 3, out_size, num_filters].
    Output [B, N, out_size * num_filters].

    SCALING NOTE: the host-side patch build materializes a dense
    [N, N, 3] eta tensor per sample — O(N^2) memory/time in node count,
    matching the reference's dense tree2col on CPU. Fine for the parse
    trees this op targets (N in the hundreds); for graphs beyond ~10^3
    nodes use paddle_tpu.geometric send_u_recv-style sparse aggregation
    instead.
    """
    from ...framework.tensor import Tensor

    def _np_of(v):
        return np.asarray(v.numpy() if isinstance(v, Tensor) else v)

    edges = _np_of(edge_set).astype(np.int64)
    B = edges.shape[0]
    N = int(nodes_vector.shape[1])

    def build_eta(sample_edges):
        """[N, N, 3] eta weights: eta[u-1, v-1] = (l, r, t) of v in u's
        patch (direct port of Tree2ColUtil::construct_patch)."""
        tr = {}
        node_count = 0
        for u, v in sample_edges:
            if u == 0 or v == 0:
                # padding rows: skip individually (reference skips any row
                # with a zero endpoint; only-(0,0) break would corrupt via
                # negative indexing and drop later real edges)
                continue
            tr.setdefault(int(u), []).append(int(v))
            node_count += 1
        node_count += 1
        eta = np.zeros((N, N, 3), np.float32)
        md = float(max_depth)
        for root in range(1, node_count + 1):
            patch = [(root, 1, 1, 0)]       # (node, index, pclen, depth)
            stack = [(root, 0)]             # DFS needs only (node, depth)
            visited = {root}
            while stack:
                node, depth = stack[-1]
                progressed = False
                for i, v in enumerate(tr.get(node, [])):
                    if v not in visited and depth + 1 < max_depth:
                        visited.add(v)
                        stack.append((v, depth + 1))
                        patch.append((v, i + 1, len(tr[node]), depth + 1))
                        progressed = True
                if not progressed:
                    stack.pop()
            for (v, idx, pclen, depth) in patch:
                eta_t = (md - depth) / md
                tmp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
                eta_l = (1.0 - eta_t) * tmp
                eta_r = (1.0 - eta_t) * (1.0 - eta_l)
                eta[root - 1, v - 1, 0] += eta_l
                eta[root - 1, v - 1, 1] += eta_r
                eta[root - 1, v - 1, 2] += eta_t
        return eta

    M = np.stack([build_eta(edges[b]) for b in range(B)])  # [B, N, N, 3]
    from ...framework.tensor import to_tensor

    def fn(m, feat, w):
        F_, K3, out_size, num_filters = w.shape
        # patch[b, p, f, k] = sum_v M[b, p, v, k] * feat[b, v, f]
        patch = jnp.einsum("bpvk,bvf->bpfk", m, feat)
        out = jnp.einsum("bpfk,fkon->bpon", patch, w)
        return out.reshape(feat.shape[0], feat.shape[1],
                           out_size * num_filters)

    # M rides as a tensor arg: the jitted kernel is shape-keyed and reused
    # across batches with different tree structures (filter_by_instag's
    # established pattern for host-computed index data)
    return op(fn, to_tensor(M), nodes_vector, filter, op_name="tree_conv")


def rank_attention(input, rank_offset, rank_param, max_rank=3,
                   name=None):
    """Rank-specific attention for CTR models (reference:
    rank_attention_op.cu): each instance selects, per visible rank slot k,
    a partner row of the input and a (own_rank, partner_rank)-specific
    block of rank_param; output = sum_k x_partner_k @ W[own, rank_k].

    input [N, d]; rank_offset [N, 1 + 2*max_rank] int: col 0 = own rank
    (1-based, <=0 invalid); then (rank_k, row_index_k) pairs with rank_k
    1-based (<=0 invalid) and row_index_k a 0-BASED row into input (the
    reference kernel's convention); rank_param [max_rank*max_rank*d, out].
    Output [N, out] in the input dtype.
    """
    def fn(x, ro, p):
        N, d = x.shape
        out_dim = p.shape[1]
        P = p.reshape(max_rank, max_rank, d, out_dim)
        own = ro[:, 0].astype(jnp.int32) - 1                   # [N]
        own_ok = own >= 0
        acc = jnp.zeros((N, out_dim), jnp.float32)
        in_dtype = x.dtype
        for k in range(max_rank):
            rk = ro[:, 2 * k + 1].astype(jnp.int32) - 1
            idx = ro[:, 2 * k + 2].astype(jnp.int32)
            ok = (own_ok & (rk >= 0)).astype(jnp.float32)      # [N]
            xk = x[jnp.clip(idx, 0, N - 1)]                    # [N, d]
            Wk = P[jnp.clip(own, 0, max_rank - 1),
                   jnp.clip(rk, 0, max_rank - 1)]              # [N, d, out]
            acc = acc + ok[:, None] * jnp.einsum(
                "nd,ndo->no", xk.astype(jnp.float32),
                Wk.astype(jnp.float32))
        return acc.astype(in_dtype)

    return op(fn, input, rank_offset, rank_param, op_name="rank_attention")

"""Attention functionals.

Reference: the fused attention CUDA ops (operators/fused/fused_attention_op.cu,
fmha_ref.h) and nn.functional attention math in
python/paddle/nn/layer/transformer.py:MultiHeadAttention.core_attn.

TPU-native: one traceable composition (matmul → scale → mask → softmax →
dropout → matmul) that XLA fuses onto the MXU; a pallas flash-attention kernel
(paddle_tpu.ops.flash_attention) and a ring-attention sequence-parallel variant
(paddle_tpu.distributed.ring_attention) plug in behind the same signature.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...framework.autograd import call_op
from ...framework.tensor import Tensor
from .common import dropout as _dropout


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """q/k/v: [batch, seq, heads, head_dim] (paddle layout). Returns the same
    layout. attn_mask broadcasts against [batch, heads, q_len, kv_len]; bool
    masks keep True positions, float masks are added to the logits.

    Unmasked dropout-free attention on TPU with kernel-friendly shapes takes
    the pallas flash kernel (paddle_tpu.ops.flash_attention) — the fused path
    the reference reaches through fused_attention_op.cu."""
    from ...framework.target import target_platform

    if (attn_mask is None and dropout_p == 0.0
            and query.shape == key.shape == value.shape
            and target_platform() == "tpu"):
        from ...framework.autograd import call_op as _call
        from ...ops.flash_attention import (
            flash_attention_sharded_ok, flash_attention_val_auto,
        )

        if flash_attention_sharded_ok(tuple(query.shape)):
            return _call(
                lambda q, k, v: flash_attention_val_auto(q, k, v,
                                                         causal=is_causal),
                query, key, value, op_name="sdpa_flash")
    scale = 1.0 / math.sqrt(query.shape[-1])

    def attn(q, k, v, *mask):
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if is_causal:
            ql, kl = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
            logits = jnp.where(causal, logits, jnp.finfo(logits.dtype).min)
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                logits = jnp.where(m, logits, jnp.finfo(logits.dtype).min)
            else:
                logits = logits + m.astype(logits.dtype)
        probs = jnp.exp(logits - jnp.max(logits, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        return probs.astype(v.dtype), None

    def full(q, k, v, *mask):
        probs, _ = attn(q, k, v, *mask)
        return probs

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    probs = call_op(full, *args, op_name="sdpa_probs")
    if dropout_p:
        probs = _dropout(probs, p=dropout_p, training=training)
    out = call_op(lambda p, v: jnp.einsum("bhqk,bkhd->bqhd", p, v), probs, value,
                  op_name="sdpa_out")
    return out

"""Sequence op family.

Reference: paddle/fluid/operators/sequence_ops/ (~7k LoC over LoD tensors:
sequence_pad/unpad/reverse/expand/pool/softmax/mask etc., exposed as
paddle.static.nn.sequence_*).

TPU-native: LoD (ragged) tensors defeat XLA's static shapes, so the carrier
is (padded data [B, T, ...], lengths [B]) — the same representation the
reference's *_pad ops convert to at the CUDA boundary. Everything below is
jit-compatible except the ops whose OUTPUT size is data-dependent
(sequence_unpad/expand), which run eagerly on host values like the
reference's LoD manipulation does on CPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.autograd import call_op as op
from ...framework.tensor import Tensor

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_reverse",
    "sequence_pool", "sequence_softmax", "sequence_expand", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
]


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """mask[i, j] = j < x[i] (reference: sequence_mask_op)."""
    from ...framework.dtype import convert_dtype

    def fn(lens):
        m = maxlen if maxlen is not None else int(jnp.max(lens))
        pos = jnp.arange(m)
        return (pos[None, ...] < lens[..., None]).astype(convert_dtype(dtype))

    return op(fn, x, op_name="sequence_mask")


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """Ragged rows (concatenated [sum(len), ...]) → padded [B, T, ...]
    (reference: sequence_pad_op). Returns (padded, lengths)."""
    lens = np.asarray(_val(lengths)).astype(np.int64)
    T = int(maxlen if maxlen is not None else lens.max())
    B = lens.size
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]])

    def fn(xv, pv):
        feat = xv.shape[1:]
        fill = jnp.full((B, T) + feat, jnp.asarray(pv, xv.dtype))
        rows = []
        for b in range(B):
            # gather with clamped indices, then mask the padding tail
            idx = np.minimum(starts[b] + np.arange(T), xv.shape[0] - 1)
            seg = xv[idx]
            valid = (np.arange(T) < lens[b]).reshape(
                (T,) + (1,) * len(feat))
            rows.append(jnp.where(valid, seg, fill[b]))
        return jnp.stack(rows)

    padded = op(fn, x, pad_value if isinstance(pad_value, Tensor)
                else Tensor(np.asarray(pad_value, np.float32)),
                op_name="sequence_pad")
    return padded, Tensor(jnp.asarray(lens), _internal=True)


def sequence_unpad(x, length, name=None):
    """Padded [B, T, ...] → concatenated [sum(len), ...] (sequence_unpad_op).
    Output size is data-dependent → eager host op."""
    lens = np.asarray(_val(length)).astype(np.int64)

    def fn(xv):
        if isinstance(xv, jax.core.Tracer):
            raise ValueError(
                "sequence_unpad's output shape depends on lengths; call it "
                "eagerly (outside jit), as the reference does on LoD host "
                "data")
        return jnp.concatenate([xv[b, :int(l)] for b, l in enumerate(lens)])

    return op(fn, x, op_name="sequence_unpad")


def sequence_reverse(x, lengths=None, name=None):
    """Reverse each sequence's valid prefix (sequence_reverse_op)."""
    def fn(xv, *rest):
        T = xv.shape[1]
        if rest:
            lens = rest[0]
            pos = jnp.arange(T)
            # index j < len → len-1-j, else j (padding stays in place)
            idx = jnp.where(pos[None, :] < lens[:, None],
                            lens[:, None] - 1 - pos[None, :], pos[None, :])
            return jnp.take_along_axis(
                xv, idx.reshape(idx.shape + (1,) * (xv.ndim - 2)).astype(
                    jnp.int32), axis=1)
        return xv[:, ::-1]

    args = [x] + ([lengths] if lengths is not None else [])
    return op(fn, *args, op_name="sequence_reverse")


def sequence_pool(x, pool_type, lengths=None, pad_value=0.0, name=None):
    """sum/average/max/min/first/last over each valid prefix
    (sequence_pool_op)."""
    pool_type = pool_type.lower()

    def fn(xv, *rest):
        B, T = xv.shape[0], xv.shape[1]
        if rest:
            lens = rest[0]
        else:
            lens = jnp.full((B,), T, jnp.int32)
        mshape = (B, T) + (1,) * (xv.ndim - 2)
        valid = (jnp.arange(T)[None, :] < lens[:, None]).reshape(mshape)
        n = jnp.maximum(lens, 1).reshape((B,) + (1,) * (xv.ndim - 2))
        if pool_type == "sum":
            return jnp.sum(jnp.where(valid, xv, 0), axis=1)
        if pool_type in ("average", "mean"):
            return jnp.sum(jnp.where(valid, xv, 0), axis=1) / n
        if pool_type == "sqrt":
            return jnp.sum(jnp.where(valid, xv, 0), axis=1) / jnp.sqrt(
                n.astype(jnp.float32))
        if pool_type == "max":
            return jnp.max(jnp.where(valid, xv, -jnp.inf), axis=1)
        if pool_type == "min":
            return jnp.min(jnp.where(valid, xv, jnp.inf), axis=1)
        if pool_type == "first":
            return xv[:, 0]
        if pool_type == "last":
            idx = jnp.maximum(lens - 1, 0).astype(jnp.int32)
            return jnp.take_along_axis(
                xv, idx.reshape((B, 1) + (1,) * (xv.ndim - 2)),
                axis=1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type!r}")

    args = [x] + ([lengths] if lengths is not None else [])
    return op(fn, *args, op_name=f"sequence_pool_{pool_type}")


def sequence_first_step(x, lengths=None):
    return sequence_pool(x, "first", lengths)


def sequence_last_step(x, lengths=None):
    return sequence_pool(x, "last", lengths)


def sequence_softmax(x, lengths=None, name=None):
    """Masked softmax over the time dim (sequence_softmax_op)."""
    def fn(xv, *rest):
        if rest:
            lens = rest[0]
            T = xv.shape[1]
            valid = jnp.arange(T)[None, :] < lens[:, None]
            valid = valid.reshape(valid.shape + (1,) * (xv.ndim - 2))
            logits = jnp.where(valid, xv, -1e30)
        else:
            logits = xv
        out = jax.nn.softmax(logits.astype(jnp.float32), axis=1)
        if rest:
            out = jnp.where(valid, out, 0.0)
        return out.astype(xv.dtype)

    args = [x] + ([lengths] if lengths is not None else [])
    return op(fn, *args, op_name="sequence_softmax")


def sequence_expand(x, repeat_times, name=None):
    """Repeat row b repeat_times[b] times (sequence_expand_op semantics on
    the padded carrier). Data-dependent output size → eager host op."""
    reps = np.asarray(_val(repeat_times)).astype(np.int64)

    def fn(xv):
        if isinstance(xv, jax.core.Tracer):
            raise ValueError("sequence_expand runs eagerly (ragged output)")
        return jnp.repeat(xv, jnp.asarray(reps), axis=0)

    return op(fn, x, op_name="sequence_expand")


def sequence_concat(inputs, name=None):
    """Concatenate along time (sequence_concat_op on padded carriers)."""
    return op(lambda *vs: jnp.concatenate(vs, axis=1), *inputs,
              op_name="sequence_concat")


def sequence_slice(x, offset, length, name=None):
    """Per-sequence slice [offset[b], offset[b]+length[b]) gathered onto a
    common max-length frame (sequence_slice_op)."""
    offs = np.asarray(_val(offset)).astype(np.int64).reshape(-1)
    lens = np.asarray(_val(length)).astype(np.int64).reshape(-1)
    T_out = int(lens.max())

    def fn(xv):
        B = xv.shape[0]
        pos = np.arange(T_out)
        idx = np.minimum(offs[:, None] + pos[None, :], xv.shape[1] - 1)
        out = jnp.take_along_axis(
            xv, jnp.asarray(idx).reshape((B, T_out) + (1,) * (xv.ndim - 2)),
            axis=1)
        valid = (pos[None, :] < lens[:, None]).reshape(
            (B, T_out) + (1,) * (xv.ndim - 2))
        return jnp.where(valid, out, 0)

    return op(fn, x, op_name="sequence_slice")

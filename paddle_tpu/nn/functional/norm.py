"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
operators/{batch_norm,layer_norm,group_norm,instance_norm}_op.*).

batch_norm threads running stats functionally: the layer owns mutable buffer
Tensors whose payloads are rebound here — under jit tracing the rebinding puts
tracers in the buffers, which the functional bridge collects as carried state.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.autograd import call_op as op, no_grad
from ...framework.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    axes = tuple(range(-len(ns), 0))

    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = (v.astype(jnp.float32) - mean) * jax_rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out

    args = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])
    return op(fn, *args, op_name="layer_norm")


def jax_rsqrt(v):
    import jax.lax

    return jax.lax.rsqrt(v)


import jax  # noqa: E402


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch_stats = training and not use_global_stats

    bshape = [1] * x.ndim
    bshape[channel_axis] = x.shape[channel_axis]

    if use_batch_stats:
        # compute batch stats (no grad through the stat update)
        stats = op(
            lambda v: (
                jnp.mean(v.astype(jnp.float32), axis=reduce_axes),
                jnp.var(v.astype(jnp.float32), axis=reduce_axes),
            ),
            x.detach(),
            op_name="bn_stats",
        )
        mean_t, var_t = stats
        # update running stats in place (reference semantics: running = m*running + (1-m)*batch)
        with no_grad():
            running_mean._value = (
                momentum * running_mean._value + (1.0 - momentum) * mean_t._value
            ).astype(running_mean._value.dtype)
            running_var._value = (
                momentum * running_var._value + (1.0 - momentum) * var_t._value
            ).astype(running_var._value.dtype)
        mean_u, var_u = mean_t, var_t
    else:
        mean_u, var_u = running_mean, running_var

    has_w, has_b = weight is not None, bias is not None

    def fn(v, m, var, *wb):
        m = m.reshape(bshape).astype(jnp.float32)
        var = var.reshape(bshape).astype(jnp.float32)
        out = (v.astype(jnp.float32) - m) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(v.dtype)
        i = 0
        if has_w:
            out = out * wb[i].reshape(bshape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(bshape)
        return out

    args = [x, mean_u, var_u] + ([weight] if has_w else []) + ([bias] if has_b else [])
    return op(fn, *args, op_name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW",
                  name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) if channel_axis == 1 else tuple(
        range(1, x.ndim - 1)
    )

    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        mean = jnp.mean(v.astype(jnp.float32), axis=reduce_axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=reduce_axes, keepdims=True)
        out = ((v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)).astype(v.dtype)
        shape = [1] * v.ndim
        shape[channel_axis] = v.shape[channel_axis]
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])
    return op(fn, *args, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW",
               name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    has_w, has_b = weight is not None, bias is not None

    def fn(v, *wb):
        c = v.shape[channel_axis]
        if channel_axis != 1:
            v_ = jnp.moveaxis(v, channel_axis, 1)
        else:
            v_ = v
        n = v_.shape[0]
        grouped = v_.reshape(n, num_groups, c // num_groups, *v_.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(grouped.astype(jnp.float32), axis=axes, keepdims=True)
        outg = ((grouped.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
        out = outg.reshape(v_.shape)
        shape = [1] * out.ndim
        shape[1] = c
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape)
            i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channel_axis != 1:
            out = jnp.moveaxis(out, 1, channel_axis)
        return out

    args = [x] + ([weight] if has_w else []) + ([bias] if has_b else [])
    return op(fn, *args, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    # out = x / (k + alpha/size * sum_window(x^2))^beta
    def fn2(v):
        channel_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        half = size // 2
        c = v.shape[channel_axis]
        acc = jnp.zeros_like(v)
        for offset in range(-half, size - half):
            src_lo, src_hi = max(0, -offset), min(c, c - offset)
            sl = [slice(None)] * v.ndim
            sl[channel_axis] = slice(src_lo, src_hi)
            dst = [slice(None)] * v.ndim
            dst[channel_axis] = slice(src_lo + offset, src_hi + offset)
            acc = acc.at[tuple(dst)].add(sq[tuple(sl)])
        return v / jnp.power(k + (alpha / size) * acc, beta)

    return op(fn2, x, op_name="local_response_norm")

"""Convolution functionals.

Reference: python/paddle/nn/functional/conv.py; CUDA kernels operators/conv_op.*
(cudnn). TPU-native: lax.conv_general_dilated — XLA tiles it onto the MXU;
weight layout OIHW, data NCHW (paddle default) with NHWC accepted.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.autograd import call_op as op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # paddle 4-D form [[0,0],[0,0],[ph,ph],[pw,pw]]
        return [tuple(p) for p in padding[-nd:]]
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, nd, data_format):
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    pad = _padding(padding, nd)
    spatial = "DHW"[-nd:]
    if data_format in (f"NC{spatial}", "NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + spatial
    else:
        lhs_spec = "N" + spatial + "C"
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, "OI" + spatial, lhs_spec)
    )

    def fn(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            bshape = [1] * out.ndim
            bshape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(bshape)
        return out

    if bias is not None:
        return op(fn, x, weight, bias, op_name=f"conv{nd}d")
    return op(fn, x, weight, op_name=f"conv{nd}d")


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, _pair(stride, 1), padding, _pair(dilation, 1), groups, 1,
                 data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, nd,
                    data_format, output_size=None):
    """Transposed conv as a lhs-dilated regular conv (the gradient-of-conv
    identity): dilate the input by `stride`, flip the kernel spatially, and pad
    each spatial dim with d*(k-1)-p. This is exactly how XLA lowers conv grads,
    so it hits the same MXU path as forward convs."""
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    opad = _pair(output_padding, nd)
    spatial = "DHW"[-nd:]
    lhs_spec = "NC" + spatial if data_format.startswith("NC") else "N" + spatial + "C"
    if isinstance(padding, str):
        if padding.upper() == "VALID":
            pad = [(0, 0)] * nd
        else:
            raise NotImplementedError("SAME padding for conv_transpose")
    else:
        pad = _padding(padding, nd)

    k = list(weight.shape[2:])
    in_c = weight.shape[0]
    out_cg = weight.shape[1]
    trans_pad = [
        (dilation[i] * (k[i] - 1) - pad[i][0],
         dilation[i] * (k[i] - 1) - pad[i][1] + opad[i])
        for i in range(nd)
    ]
    dn_shape_rhs = (in_c // groups, out_cg * groups) + tuple(k)
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), dn_shape_rhs, (lhs_spec, "IO" + spatial, lhs_spec)
    )

    def fn(v, w, *rest):
        # [in, out/g, *k] -> [g, in/g, out/g, *k] -> [in/g, g, out/g, *k] -> [in/g, out, *k]
        wg = w.reshape((groups, in_c // groups, out_cg) + tuple(k))
        wg = jnp.swapaxes(wg, 0, 1).reshape((in_c // groups, out_cg * groups) + tuple(k))
        wg = jnp.flip(wg, axis=tuple(range(2, 2 + nd)))
        out = jax.lax.conv_general_dilated(
            v, wg, window_strides=(1,) * nd, padding=trans_pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            bshape = [1] * out.ndim
            bshape[lhs_spec.index("C")] = b.shape[0]
            out = out + b.reshape(bshape)
        return out

    if bias is not None:
        return op(fn, x, weight, bias, op_name=f"conv{nd}d_transpose")
    return op(fn, x, weight, op_name=f"conv{nd}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           3, data_format, output_size)

"""Activation functionals (reference: python/paddle/nn/functional/activation.py;
CUDA kernels in paddle/fluid/operators/activation_op.*). All lower to XLA
elementwise HLO and fuse into neighboring matmuls on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.autograd import call_op as op
from ...framework.tensor import Tensor


def relu(x, name=None):
    return op(jax.nn.relu, x, op_name="relu")


def relu_(x, name=None):
    x._replace_from(relu(x))
    return x


def relu6(x, name=None):
    return op(jax.nn.relu6, x, op_name="relu6")


def gelu(x, approximate=False, name=None):
    return op(lambda v: jax.nn.gelu(v, approximate=approximate), x, op_name="gelu")


def sigmoid(x, name=None):
    return op(jax.nn.sigmoid, x, op_name="sigmoid")


def tanh(x, name=None):
    return op(jnp.tanh, x, op_name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...framework.dtype import convert_dtype

            v = v.astype(convert_dtype(dtype))
        return jax.nn.softmax(v, axis=axis)

    return op(fn, x, op_name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    x._replace_from(softmax(x, axis, dtype))
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(v):
        if dtype is not None:
            from ...framework.dtype import convert_dtype

            v = v.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(v, axis=axis)

    return op(fn, x, op_name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return op(lambda v: jax.nn.leaky_relu(v, negative_slope), x, op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)

    return op(fn, x, weight, op_name="prelu")


def elu(x, alpha=1.0, name=None):
    return op(lambda v: jax.nn.elu(v, alpha), x, op_name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x, op_name="selu")


def celu(x, alpha=1.0, name=None):
    return op(lambda v: jax.nn.celu(v, alpha), x, op_name="celu")


def silu(x, name=None):
    return op(jax.nn.silu, x, op_name="silu")


def swish(x, name=None):
    return op(jax.nn.silu, x, op_name="swish")


def mish(x, name=None):
    return op(lambda v: v * jnp.tanh(jax.nn.softplus(v)), x, op_name="mish")


def hardswish(x, name=None):
    return op(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x, op_name="hardswish")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return op(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), x, op_name="hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return op(lambda v: jnp.clip(v, min, max), x, op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x, op_name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return op(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        x,
        op_name="softshrink",
    )


def tanhshrink(x, name=None):
    return op(lambda v: v - jnp.tanh(v), x, op_name="tanhshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return op(
        lambda v: jnp.where(beta * v > threshold, v, jax.nn.softplus(beta * v) / beta),
        x,
        op_name="softplus",
    )


def softsign(x, name=None):
    return op(jax.nn.soft_sign, x, op_name="softsign")


def log_sigmoid(x, name=None):
    return op(jax.nn.log_sigmoid, x, op_name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        ax = axis + v.ndim if axis < 0 else axis
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1 :]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return op(fn, x, op_name="maxout")


def thresholded_relu(x, threshold=1.0, name=None):
    return op(lambda v: jnp.where(v > threshold, v, 0.0), x, op_name="thresholded_relu")


def glu(x, axis=-1, name=None):
    return op(lambda v: jax.nn.glu(v, axis=axis), x, op_name="glu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key

    k = next_key()

    def fn(v):
        g = jax.random.gumbel(k, v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            # straight-through estimator: hard forward, soft gradient
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return op(fn, x, op_name="gumbel_softmax")

"""Loss functionals (reference: python/paddle/nn/functional/loss.py;
operators/softmax_with_cross_entropy_op.*, cross_entropy utilities)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.autograd import call_op as op
from ...framework.tensor import Tensor


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def fn(logits, lbl, *rest):
        w = rest[0] if rest else None
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label:
            soft = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                soft = (1 - label_smoothing) * soft + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            ids = lbl
            if ids.ndim == logp.ndim:  # [..., 1] form
                ids = jnp.squeeze(ids, axis=axis)
            ids = ids.astype(jnp.int32)
            safe = jnp.where(ids == ignore_index, 0, ids)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis
            ).squeeze(axis)
            if label_smoothing > 0:
                k = logits.shape[axis]
                smooth_loss = -jnp.mean(logp, axis=axis)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            mask = ids != ignore_index
            loss = jnp.where(mask, loss, 0.0)
            if w is not None:
                wsel = jnp.take(w, safe, axis=0)
                loss = loss * jnp.where(mask, wsel, 0.0)
                if reduction == "mean":
                    denom = jnp.maximum(jnp.sum(jnp.where(mask, wsel, 0.0)), 1e-12)
                    return jnp.sum(loss) / denom
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return op(fn, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, reduction="none", soft_label=soft_label,
                         ignore_index=ignore_index, axis=axis)
    # reference keeps the trailing [*, 1] dim for hard labels
    if not soft_label:
        from ...tensor import unsqueeze

        loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax

        return loss, softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
              op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label, op_name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d / delta, jnp.abs(d) - 0.5 * delta)
        # paddle multiplies by delta for huber form
        return _reduce(loss * delta, reduction)

    return op(fn, input, label, op_name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(logp, ids, *rest):
        w = rest[0] if rest else None
        ids = ids.astype(jnp.int32)
        safe = jnp.where(ids == ignore_index, 0, ids)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        mask = ids != ignore_index
        loss = jnp.where(mask, loss, 0.0)
        if w is not None:
            wsel = jnp.take(w, safe, axis=0)
            loss = loss * jnp.where(mask, wsel, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(mask, wsel, 0.0)), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return op(fn, *args, op_name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return op(fn, *args, op_name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, y, *rest):
        idx = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[idx]
            idx += 1
        if pos_weight is not None:
            pw = rest[idx]
        # stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight on the y term
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return op(fn, *args, op_name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return op(fn, input, label, op_name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return op(
        lambda a, b, y: _reduce(jnp.maximum(-y * (a - b) + margin, 0.0), reduction),
        input, other, label, op_name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return op(
        lambda x, y: _reduce(
            jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x)), reduction
        ),
        input, label,
    )


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12
        )
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return op(fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return op(fn, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    return op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        input, label,
    )


def square_error_cost(input, label):
    return op(lambda a, b: jnp.square(a - b), input, label, op_name="square_error_cost")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference: operators/math/sequence_scale + warpctc op,
    python/paddle/nn/functional/loss.py ctc_loss).

    log_probs: [T, B, C] logits (softmax applied internally, reference
    semantics); labels: [B, L] padded label ids; lengths: [B].

    TPU-native: the alpha recursion runs in log-space under lax.scan over T
    with the labels padded+masked to static shapes (no LoD) and vmap over
    the batch — one fused XLA program, fully differentiable.
    """
    def fn(lp, lab, in_len, lab_len):
        T, B, C = lp.shape
        L = lab.shape[1]
        logp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        NEG = -1e30

        def one(logp_b, lab_b, t_len, l_len):
            # extended label sequence: blank, l1, blank, l2, ..., blank
            S = 2 * L + 1
            ext = jnp.full((S,), blank, jnp.int32)
            ext = ext.at[1::2].set(lab_b.astype(jnp.int32))
            s_idx = jnp.arange(S)
            valid_s = s_idx < 2 * l_len + 1
            # can alpha skip from s-2? only between distinct non-blank labels
            prev2 = jnp.roll(ext, 2)
            can_skip = (s_idx % 2 == 1) & (s_idx >= 2) & (ext != prev2)

            alpha0 = jnp.full((S,), NEG)
            alpha0 = alpha0.at[0].set(logp_b[0, blank])
            alpha0 = alpha0.at[1].set(
                jnp.where(l_len > 0, logp_b[0, ext[1]], NEG))

            def step(alpha, logp_t):
                stay = alpha
                from1 = jnp.concatenate([jnp.array([NEG]), alpha[:-1]])
                from2 = jnp.concatenate([jnp.array([NEG, NEG]), alpha[:-2]])
                from2 = jnp.where(can_skip, from2, NEG)
                merged = jnp.logaddexp(jnp.logaddexp(stay, from1), from2)
                new = merged + logp_t[ext]
                return jnp.where(valid_s, new, NEG), None

            def masked_step(carry, inp):
                alpha, t = carry
                logp_t = inp
                new, _ = step(alpha, logp_t)
                # past this sequence's input length: freeze alpha
                new = jnp.where(t < t_len, new, alpha)
                return (new, t + 1), None

            (alpha, _), _ = jax.lax.scan(masked_step, (alpha0, 1), logp_b[1:])
            end1 = alpha[jnp.maximum(2 * l_len, 0)]
            end2 = jnp.where(l_len > 0,
                             alpha[jnp.maximum(2 * l_len - 1, 0)], NEG)
            ll = jnp.logaddexp(end1, end2)
            loss = -ll
            if norm_by_times:
                loss = loss / jnp.maximum(t_len.astype(jnp.float32), 1.0)
            return loss

        losses = jax.vmap(one, in_axes=(1, 0, 0, 0))(
            logp, lab, in_len.astype(jnp.int32), lab_len.astype(jnp.int32))
        if reduction == "mean":
            # reference divides each sample's loss by its label length
            return jnp.mean(
                losses / jnp.maximum(lab_len.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    args = [log_probs, labels, input_lengths, label_lengths]
    return op(*( [fn] + args ), op_name="ctc_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return op(fn, *args, op_name="sigmoid_focal_loss")


# ------------------------------- loss tail (reference nn/functional/loss.py)

def soft_margin_loss(input, label, reduction="mean", name=None):
    def fn(x, y):
        loss = jnp.log1p(jnp.exp(-y.astype(x.dtype) * x))
        return _reduce(loss, reduction)

    return op(fn, input, label, op_name="soft_margin_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(jnp.maximum(y, 1.0)) - y + 0.5 * jnp.log(
                2 * jnp.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return op(fn, input, label, op_name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        v = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(v) + (y - mu) ** 2 / v)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, mu.dtype))
        return _reduce(loss, reduction)

    return op(fn, input, label, variance, op_name="gaussian_nll_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    def fn(x, y, *rest):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y.reshape(-1, 1), axis=1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        if rest:
            m = m * rest[0][None, :]
        mask = jax.nn.one_hot(y, c, dtype=x.dtype)
        loss = jnp.sum(m * (1 - mask), axis=1) / c
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return op(fn, *args, op_name="multi_margin_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss for segmentation (reference: fluid/layers/nn.py dice_loss):
    1 - 2*|X∩Y| / (|X|+|Y|), reduced over all but the batch dim."""
    def fn(pred, lbl):
        lbl_oh = jax.nn.one_hot(lbl.reshape(lbl.shape[:-1]),
                                pred.shape[-1], dtype=pred.dtype)
        red = tuple(range(1, pred.ndim))
        inter = jnp.sum(pred * lbl_oh, red)
        union = jnp.sum(pred, red) + jnp.sum(lbl_oh, red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return op(fn, input, label, op_name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair metric learning loss (reference: fluid/layers/nn.py
    npair_loss): softmax cross-entropy over anchor·positiveᵀ similarities
    with same-label targets, plus an L2 term on the embeddings."""
    def fn(a, p, lbl):
        l = lbl.reshape(-1)
        sim = a @ p.T                                   # [B, B]
        tgt = (l[:, None] == l[None, :]).astype(sim.dtype)
        tgt = tgt / jnp.maximum(tgt.sum(-1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = -jnp.mean(jnp.sum(tgt * logp, -1))
        # reference nn.py npair_loss: l2 term scaled by l2_reg * 0.25
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1)) +
                        jnp.mean(jnp.sum(p * p, -1))) * 0.25
        return ce + reg

    return op(fn, anchor, positive, labels, op_name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: hierarchical_sigmoid_op.cc).

    Default (complete binary tree over num_classes): each class's root-path
    is derived from its index; the loss is the sum of binary logistic
    losses along the path. Custom trees pass path_table [N, L] (node ids
    into weight's rows, -1 padding) and path_code [N, L] (0/1 branch
    directions).
    """
    import numpy as np

    C = int(num_classes)
    depth = max(int(np.ceil(np.log2(max(C, 2)))) + 1, 1)

    def default_paths(lbl):
        # the reference's SimpleCode (matrix_bit_code.h): leaf id c+C in a
        # heap-indexed complete tree; level i's internal node is
        # (c >> (i+1)) - 1 (unique in [0, C-1)), branch bit (c >> i) & 1
        c = lbl.astype(jnp.int32) + C
        tables, codes = [], []
        for i in range(depth):
            parent = c >> (i + 1)
            valid = parent >= 1
            tables.append(jnp.where(valid, parent - 1, -1))
            codes.append(jnp.where(valid, (c >> i) & 1, -1))
        return jnp.stack(tables, -1), jnp.stack(codes, -1)

    def fn(x, lbl, w, *rest):
        b = rest[0] if bias is not None else None
        if path_table is not None:
            pt = jnp.asarray(path_table.numpy() if hasattr(
                path_table, "numpy") else path_table)
            pc = jnp.asarray(path_code.numpy() if hasattr(
                path_code, "numpy") else path_code)
        else:
            pt, pc = default_paths(lbl.reshape(-1))
        # logits along each path node: [B, L]
        wn = w[pt]                                    # [B, L, D]
        logit = jnp.einsum("bd,bld->bl", x, wn)
        if b is not None:
            logit = logit + b.reshape(-1)[pt]
        valid = (pc >= 0)
        # binary logistic: code 1 -> sigmoid(logit), 0 -> 1-sigmoid
        ll = jax.nn.log_sigmoid(jnp.where(pc == 1, logit, -logit))
        per = -jnp.sum(jnp.where(valid, ll, 0.0), -1)
        return per.reshape(-1, 1)

    args = [input, label, weight] + ([bias] if bias is not None else [])
    return op(fn, *args, op_name="hsigmoid_loss")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference:
    class_center_sample_op.cu, PartialFC): returns (remapped_label,
    sampled_class_indices) where positives keep their (remapped) ids and
    num_samples total classes are kept."""
    import numpy as np

    lbl = np.asarray(label.numpy() if hasattr(label, "numpy")
                     else label).reshape(-1)
    pos = np.unique(lbl)
    n_extra = max(int(num_samples) - pos.size, 0)
    rest = np.setdiff1d(np.arange(int(num_classes)), pos)
    if n_extra > 0 and rest.size:
        extra = np.random.choice(rest, min(n_extra, rest.size),
                                 replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    else:
        sampled = pos
    remap = {int(c): i for i, c in enumerate(sampled)}
    from ...framework.tensor import to_tensor

    new_lbl = np.asarray([remap[int(c)] for c in lbl], np.int64)
    return (to_tensor(new_lbl.reshape(np.asarray(
        label.numpy() if hasattr(label, "numpy") else label).shape)),
        to_tensor(sampled.astype(np.int64)))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """Combined-margin (ArcFace/CosFace/SphereFace) softmax loss
    (reference: margin_cross_entropy_op.cu): the target logit cos(θ) is
    replaced by cos(m1·θ + m2) − m3 before the scaled softmax."""
    def fn(lg, lbl):
        l = lbl.reshape(-1)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(l, lg.shape[-1], dtype=lg.dtype)
        adj = (cos * (1 - oh) + tgt * oh) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        nll = -jnp.take_along_axis(logp, l[:, None], -1)[:, 0]
        sm = jnp.exp(logp)
        if reduction == "mean":
            out = jnp.mean(nll)
        elif reduction == "sum":
            out = jnp.sum(nll)
        else:
            out = nll.reshape(-1, 1)
        return (out, sm) if return_softmax else out

    return op(fn, logits, label, op_name="margin_cross_entropy")


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference: rank_loss_op.cc):
    C = log(1 + exp(o)) - t*o with o = left - right."""
    def fn(t, l, r):
        o = l - r
        return jnp.logaddexp(0.0, o) - t * o

    return op(fn, label, left, right, op_name="rank_loss")


def bpr_loss(input, label, name=None):
    """Bayesian Personalized Ranking loss (reference: bpr_loss_op.cc):
    -mean over j != y of log sigmoid(x[y] - x[j])."""
    def fn(lg, lbl):
        B, C = lg.shape
        y = lbl.reshape(-1).astype(jnp.int32)
        pos = jnp.take_along_axis(lg, y[:, None], axis=-1)
        diff = pos - lg
        logsig = jax.nn.log_sigmoid(diff)
        mask = jnp.ones((B, C)).at[jnp.arange(B), y].set(0.0)
        return (-(logsig * mask).sum(-1) / (C - 1)).reshape(-1, 1)

    return op(fn, input, label, op_name="bpr_loss")


def center_loss(input, label, centers, alpha=0.1, update_center=True,
                name=None):
    """Center loss (reference: center_loss_op.cc, Wen et al.): pulls each
    feature toward its class center; centers update with rate alpha when
    update_center (host-side, like the reference's in-op update).

    Returns the per-sample loss [B, 1]; `centers` is a Tensor updated in
    place when update_center=True.
    """
    import numpy as np

    def fn(v, lbl, ctr):
        y = lbl.reshape(-1).astype(jnp.int32)
        diff = v - ctr[y]
        return 0.5 * jnp.sum(diff * diff, -1, keepdims=True)

    out = op(fn, input, label, centers, op_name="center_loss")
    if update_center:
        v = np.asarray(input.numpy(), np.float32)
        y = np.asarray(label.numpy()).reshape(-1).astype(np.int64)
        ctr = np.array(centers.numpy(), np.float32)  # writable copy
        for cls in np.unique(y):
            sel = v[y == cls]
            delta = (ctr[cls] - sel).sum(0) / (1.0 + sel.shape[0])
            ctr[cls] = ctr[cls] - alpha * delta
        import jax.numpy as _jnp

        centers._value = _jnp.asarray(ctr, centers._value.dtype)
    return out

"""paddle.nn.functional.flash_attention — public fused-attention API.

Parity: python/paddle/nn/functional/flash_attention.py of the reference
(flash_attention, flash_attn_unpadded, scaled_dot_product_attention), whose
CUDA backend is operators/fused/fused_attention_op.cu. Here the backend is
the Pallas TPU kernel (paddle_tpu/ops/flash_attention.py) when running on
TPU with kernel-friendly shapes, else the fused XLA composition.

All entry points take [batch, seq, heads, head_dim] and return the same
layout, like the reference.
"""
from __future__ import annotations

import jax

from ...framework.autograd import call_op
from .attention import scaled_dot_product_attention

__all__ = ["flash_attention", "flash_attn_unpadded",
           "scaled_dot_product_attention"]


def _use_kernel(q_shape, dropout):
    from ...framework.target import target_platform
    from ...ops.flash_attention import flash_attention_sharded_ok

    return (dropout == 0.0 and target_platform() == "tpu"
            and flash_attention_sharded_ok(tuple(q_shape)))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """Returns (out, softmax). softmax is None unless return_softmax — the
    flash path never materializes it (that is the point of the kernel)."""
    if return_softmax:
        raise ValueError(
            "return_softmax=True is unsupported: flash attention never "
            "materializes the probability matrix")
    if _use_kernel(query.shape, dropout):
        from ...ops.flash_attention import flash_attention_val_auto

        out = call_op(
            lambda q, k, v: flash_attention_val_auto(q, k, v,
                                                     causal=causal),
            query, key, value, op_name="flash_attention")
    else:
        out = scaled_dot_product_attention(
            query, key, value, attn_mask=None, dropout_p=dropout,
            is_causal=causal, training=training)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Varlen API shim: runs the padded kernel per the max seqlens.

    The reference packs ragged batches through cu_seqlens
    (flash_attn_unpadded); on TPU ragged shapes defeat XLA tiling, so this
    shim documents the contract and serves the common equal-length case.
    """
    import jax.numpy as jnp

    import numpy as np

    cu_q = np.asarray(cu_seqlens_q.numpy() if hasattr(cu_seqlens_q, "numpy")
                      else cu_seqlens_q)
    lens = np.diff(cu_q)
    if len(set(lens.tolist())) != 1:
        raise NotImplementedError(
            "flash_attn_unpadded on TPU requires equal sequence lengths "
            "(pad the batch); ragged packing defeats XLA tiling")
    s = int(lens[0])
    b = len(lens)

    def reshape3(t):
        return call_op(lambda v: v.reshape(b, s, *v.shape[1:]), t,
                       op_name="unpad_reshape")

    q3, k3, v3 = reshape3(query), reshape3(key), reshape3(value)
    out, _ = flash_attention(q3, k3, v3, dropout=dropout, causal=causal)
    return call_op(lambda v: v.reshape(b * s, *v.shape[2:]), out,
                   op_name="unpad_flatten"), None

"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py;
operators/pool_op.*). lax.reduce_window lowers to fused TPU window reductions."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.autograd import call_op as op


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _pad_cfg(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nd:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    return [tuple(p) for p in padding[-nd:]]


def _window(x_ndim, ksize, stride, nd, channel_last):
    if channel_last:
        dims = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
    else:
        dims = (1, 1) + ksize
        strides = (1, 1) + stride
    return dims, strides


def _full_pad(pad, nd, channel_last):
    if channel_last:
        return [(0, 0)] + list(pad) + [(0, 0)]
    return [(0, 0), (0, 0)] + list(pad)


def _ceil_extra(size, k, s, lo, hi):
    """Extra high padding so the last (ceil-mode) window is covered."""
    span = size + lo + hi
    out_floor = (span - k) // s + 1
    out_ceil = -(-(span - k) // s) + 1
    if out_ceil > out_floor:
        return (out_ceil - 1) * s + k - span
    return 0


def _pool(x, ksize, stride, padding, nd, mode, ceil_mode=False, exclusive=True,
          data_format="NCHW"):
    ksize = _pair(ksize, nd)
    stride = _pair(stride if stride is not None else ksize, nd)
    channel_last = not data_format.startswith("NC")
    pad = _pad_cfg(padding, nd)
    if isinstance(pad, str):
        pad_seq = pad  # SAME / VALID
    else:
        if ceil_mode:
            spatial = x.shape[1:-1] if channel_last else x.shape[2:]
            pad = [
                (lo, hi + _ceil_extra(sz, k, s, lo, hi))
                for (lo, hi), sz, k, s in zip(pad, spatial, ksize, stride)
            ]
        pad_seq = _full_pad(pad, nd, channel_last)
    dims, strides = _window(x.ndim, ksize, stride, nd, channel_last)

    def fn(v):
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            return jax.lax.reduce_window(v, init, jax.lax.max, dims, strides, pad_seq)
        # avg
        ones = jnp.ones_like(v)
        s = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, pad_seq)
        if exclusive and not isinstance(pad_seq, str):
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad_seq)
            return s / cnt
        return s / np.prod(ksize)

    return op(fn, x, op_name=f"{mode}_pool{nd}d")


def _max_pool_with_mask(x, ksize, stride, padding, nd, ceil_mode, data_format):
    """Reference max_pool return_mask semantics: indices into the flattened
    spatial input (operators/pool_with_index_op). Implemented via
    conv_general_dilated_patches + argmax over the window axis."""
    if data_format.startswith("NC") is False:
        raise NotImplementedError("return_mask requires channel-first layout")
    ksize = _pair(ksize, nd)
    stride = _pair(stride if stride is not None else ksize, nd)
    pad = _pad_cfg(padding, nd)
    if isinstance(pad, str):
        raise NotImplementedError("return_mask with string padding")
    spatial = x.shape[2:]
    if ceil_mode:
        pad = [
            (lo, hi + _ceil_extra(sz, k, s, lo, hi))
            for (lo, hi), sz, k, s in zip(pad, spatial, ksize, stride)
        ]

    def fn(v):
        n, c = v.shape[0], v.shape[1]
        neg = jnp.finfo(v.dtype).min
        vp = jnp.pad(v, [(0, 0), (0, 0)] + [(lo, hi) for lo, hi in pad],
                     constant_values=neg)
        patches = jax.lax.conv_general_dilated_patches(
            vp, filter_shape=ksize, window_strides=stride, padding=[(0, 0)] * nd,
        )  # [N, C*prod(k), *out_spatial] with channel-major patch layout
        out_sp = patches.shape[2:]
        kk = int(np.prod(ksize))
        patches = patches.reshape((n, c, kk) + out_sp)
        vals = jnp.max(patches, axis=2)
        widx = jnp.argmax(patches, axis=2)  # window-local flat index
        # decode to global (padded) coords, then to unpadded flat spatial index
        padded_sp = vp.shape[2:]
        coords = []
        rem = widx
        for d in range(nd - 1, -1, -1):
            coords.insert(0, rem % ksize[d])
            rem = rem // ksize[d]
        flat = jnp.zeros_like(widx)
        for d in range(nd):
            base = jnp.arange(out_sp[d]) * stride[d]
            shape = [1] * widx.ndim
            shape[2 + d] = out_sp[d]
            gcoord = coords[d] + base.reshape(shape) - pad[d][0]
            gcoord = jnp.clip(gcoord, 0, spatial[d] - 1)
            flat = flat * spatial[d] + gcoord
        return vals, flat.astype("int32")

    return op(fn, x, op_name=f"max_pool{nd}d_mask")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1, ceil_mode, data_format)
    return _pool(x, kernel_size, stride, padding, 1, "max", ceil_mode, data_format=data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2, ceil_mode, data_format)
    return _pool(x, kernel_size, stride, padding, 2, "max", ceil_mode, data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3, ceil_mode, data_format)
    return _pool(x, kernel_size, stride, padding, 3, "max", ceil_mode, data_format=data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", ceil_mode, exclusive, data_format)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", ceil_mode, exclusive, data_format)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", ceil_mode, exclusive, data_format)


def _adaptive(x, output_size, nd, mode, data_format):
    channel_last = not data_format.startswith("NC")
    out_sp = _pair(output_size, nd)

    def fn(v):
        spatial = v.shape[1:-1] if channel_last else v.shape[2:]
        # uniform windows when divisible — the common case — else resize trick
        if all(s % o == 0 for s, o in zip(spatial, out_sp)):
            ks = tuple(s // o for s, o in zip(spatial, out_sp))
            dims, strides = _window(v.ndim, ks, ks, nd, channel_last)
            if mode == "max":
                return jax.lax.reduce_window(v, -jnp.inf, jax.lax.max, dims, strides, "VALID")
            s = jax.lax.reduce_window(v, 0.0, jax.lax.add, dims, strides, "VALID")
            return s / np.prod(ks)
        # non-divisible: per-output-cell reduction
        axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
        out = v
        for i, (ax, o) in enumerate(zip(axes, out_sp)):
            size = out.shape[ax]
            starts = np.floor(np.arange(o) * size / o).astype(int)
            ends = np.ceil((np.arange(o) + 1) * size / o).astype(int)
            slices = []
            for s0, e0 in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s0, e0, axis=ax)
                red = jnp.max(seg, axis=ax, keepdims=True) if mode == "max" else jnp.mean(
                    seg, axis=ax, keepdims=True
                )
                slices.append(red)
            out = jnp.concatenate(slices, axis=ax)
        return out

    return op(fn, x, op_name=f"adaptive_{mode}_pool{nd}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 1)
    return _adaptive(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 2)
    return _adaptive(x, output_size, 2, "max", "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        return _adaptive_max_mask(x, output_size, 3)
    return _adaptive(x, output_size, 3, "max", "NCDHW")


def _adaptive_max_mask(x, output_size, nd):
    out_sp = _pair(output_size, nd)
    spatial = x.shape[2:]
    if not all(s % o == 0 for s, o in zip(spatial, out_sp)):
        raise NotImplementedError(
            "adaptive max pool return_mask requires divisible spatial dims"
        )
    ks = tuple(s // o for s, o in zip(spatial, out_sp))
    return _max_pool_with_mask(x, ks, ks, 0, nd, False, "NC")

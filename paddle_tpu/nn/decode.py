"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py (~900 LoC: Decoder protocol,
BeamSearchDecoder with tiled-batch beams, dynamic_decode driving
step/finalize until finished).

TPU-native note: the per-step compute (cell + projection + top-k) is
compiled work; the decode LOOP runs host-side like the reference's dygraph
path — decode lengths are data-dependent, which is exactly what XLA's
static shapes can't absorb, and serving decodes are latency- not
throughput-bound. Beam bookkeeping is vectorized numpy on host, gathers on
device."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.tensor import Tensor
from .. import tensor as ops

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Decode protocol (reference decode.py Decoder): initialize → step* →
    finalize."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference decode.py BeamSearchDecoder).

    cell: an RNNCell-like layer: (emb, states) -> (out, new_states);
    embedding_fn maps token ids → embeddings; output_fn maps cell output →
    vocab logits.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers -------------------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[b, ...] → [b*beam, ...] (reference helper of the same name)."""
        v = x._value if isinstance(x, Tensor) else x
        import jax.numpy as jnp

        tiled = jnp.repeat(v, beam_size, axis=0)
        return Tensor(tiled, _internal=True)

    def initialize(self, initial_cell_states):
        b = None
        leaves = (initial_cell_states
                  if isinstance(initial_cell_states, (tuple, list))
                  else [initial_cell_states])
        b = leaves[0].shape[0]
        K = self.beam_size
        states = self._map_states(
            initial_cell_states,
            lambda t: self.tile_beam_merge_with_batch(t, K))
        ids = np.full((b * K,), self.start_token, np.int64)
        # only beam 0 live initially (standard -inf trick)
        log_probs = np.full((b, K), -1e9, np.float32)
        log_probs[:, 0] = 0.0
        finished = np.zeros((b, K), bool)
        return ids, (states, log_probs, finished)

    def _map_states(self, states, fn):
        if isinstance(states, (tuple, list)):
            return type(states)(self._map_states(s, fn) for s in states)
        return fn(states)

    def step(self, time, inputs, states, **kwargs):
        cell_states, log_probs, finished = states
        b, K = log_probs.shape
        emb = (self.embedding_fn(Tensor(np.asarray(inputs)))
               if self.embedding_fn is not None
               else Tensor(np.asarray(inputs, np.float32)))
        cell_out, new_cell_states = self.cell(emb, cell_states)
        logits = (self.output_fn(cell_out) if self.output_fn is not None
                  else cell_out)
        logp = _log_softmax(np.asarray(logits.numpy(), np.float64))
        V = logp.shape[-1]
        logp = logp.reshape(b, K, V)
        # finished beams only extend with end_token at zero cost
        fin_mask = np.full((V,), -1e9)
        fin_mask[self.end_token] = 0.0
        logp = np.where(finished[:, :, None], fin_mask[None, None, :], logp)
        total = log_probs[:, :, None] + logp              # [b, K, V]
        flat = total.reshape(b, K * V)
        top = np.argsort(-flat, axis=1, kind="stable")[:, :K]
        new_log_probs = np.take_along_axis(flat, top, axis=1).astype(
            np.float32)
        beam_idx = top // V                               # [b, K]
        token_idx = (top % V).astype(np.int64)
        new_finished = np.take_along_axis(finished, beam_idx, axis=1) | (
            token_idx == self.end_token)
        gather = (np.arange(b)[:, None] * K + beam_idx).reshape(-1)

        def regather(t):
            v = t._value if isinstance(t, Tensor) else t
            return Tensor(v[gather], _internal=True)

        new_cell_states = self._map_states(new_cell_states, regather)
        next_ids = token_idx.reshape(-1)
        outputs = {"token": token_idx, "parent": beam_idx}
        return outputs, next_ids, (new_cell_states, new_log_probs,
                                   new_finished), new_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers → [b, K, T] token matrix, best-first."""
        tokens = np.stack([o["token"] for o in outputs], axis=-1)  # [b,K,T]
        parents = np.stack([o["parent"] for o in outputs], axis=-1)
        b, K, T = tokens.shape
        out = np.zeros((b, K, T), np.int64)
        for bi in range(b):
            for k in range(K):
                beam = k
                for t in range(T - 1, -1, -1):
                    out[bi, k, t] = tokens[bi, beam, t]
                    beam = parents[bi, beam, t]
        _, log_probs, _ = final_states
        order = np.argsort(-log_probs, axis=1, kind="stable")
        out = np.take_along_axis(out, order[:, :, None], axis=1)
        return Tensor(out), final_states


def _log_softmax(x):
    m = x.max(-1, keepdims=True)
    e = np.exp(x - m)
    return (x - m) - np.log(e.sum(-1, keepdims=True))


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Drive decoder.initialize/step until every beam finishes or
    max_step_num (reference decode.py dynamic_decode)."""
    inputs, states = decoder.initialize(inits)
    outputs = []
    b = None
    seq_len = None
    T = int(max_step_num or 64)
    for t in range(T):
        out, inputs, states, finished = decoder.step(t, inputs, states,
                                                     **kwargs)
        outputs.append(out)
        fin = np.asarray(finished)
        if seq_len is None:
            seq_len = np.full(fin.shape, T, np.int64)
        newly = (fin) & (seq_len == T)
        seq_len = np.where(newly, t + 1, seq_len)
        if fin.all():
            break
    final, final_states = decoder.finalize(outputs, states, seq_len)
    if return_length:
        return final, final_states, Tensor(seq_len)
    return final, final_states

"""nn.Layer — the module base class.

Reference: python/paddle/fluid/dygraph/layers.py (paddle.nn.Layer): parameter /
sublayer / buffer registries via __setattr__, structured state_dict, forward
hooks, train/eval flags. TPU-native addition: ``create_parameter`` accepts a
``dist_spec`` (jax PartitionSpec) consumed by the pjit bridge for sharded
training.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from ...framework import dtype as dtype_mod
from ...framework.param_attr import ParamAttr
from ...framework.tensor import Parameter, Tensor
from .. import initializer as I


class HookRemoveHelper:
    _next_id = [0]

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id[0]
        HookRemoveHelper._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype) if dtype else dtype_mod.float32
        # per-instance name "<layer>_N" (reference fluid/unique_name.py
        # semantics); auto-generated parameter names build on it
        from ...utils import unique_name

        self._full_name = unique_name.generate(
            name_scope or self.__class__.__name__.lower())
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()

    # ------------------------------------------------------------ registry
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
            layers[name] = value
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                else:
                    raise TypeError(f"cannot assign non-Parameter to parameter {name!r}")
            if layers is not None and name in layers:
                layers.pop(name)
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------ creation
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
        dist_spec=None,
    ) -> Parameter:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype_mod.convert_dtype(dtype) if dtype else self._dtype
        init = attr.initializer or default_initializer or I.global_initializer(is_bias)
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        if attr.name:
            pname = attr.name
        else:
            # reference auto-naming (fluid/unique_name.py): every param
            # gets "<layer>_N.w_M" / "<layer>_N.b_M" — the name-based
            # decay-exclusion APIs (AdamW apply_decay_param_fun, Lamb/Lars
            # exclude lists) key on these conventions
            from ...utils import unique_name

            pname = unique_name.generate(
                f"{self._full_name}.{'b' if is_bias else 'w'}")
        p = Parameter(np.zeros([int(s) for s in shape], dtype="float32"), dtype=dtype,
                      name=pname, trainable=attr.trainable)
        # optimizer.set_state_dict distrusts auto-generated names on
        # partial checkpoint overlap (the counter shifts between builds)
        # but always trusts user-chosen ones
        p._auto_named = not attr.name
        init(p)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        if dist_spec is not None:
            p.dist_spec = dist_spec
            p.is_distributed = True
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            setattr(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ iteration
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def _traverse(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, "Layer"]]:
        yield prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, True)

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, sub in self._sub_layers.items():
            if sub is not None and id(sub) not in seen:
                seen.add(id(sub))
                yield name, sub

    def sublayers(self, include_self=False):
        res = []
        for _, l in self._traverse("", True):
            res.append(l)
        return res if include_self else res[1:]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        for name, l in self._traverse(prefix, True):
            if not include_self and l is self:
                continue
            yield name, l

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------ modes
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="",
                   use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        seen = set()
        for prefix, layer in self._traverse("", include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                # persistability is the OWNING layer's property
                if bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{prefix}.{bname}" if prefix else bname
                dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            if tuple(arr.shape) != tuple(tgt._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {arr.shape} vs {tuple(tgt._value.shape)}"
                )
            tgt.set_value(arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ------------------------------------------------------------ conversion
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtype_mod.convert_dtype(dtype)
            for p in self.parameters():
                if dtype_mod.is_floating_point(p.dtype):
                    p._value = p._value.astype(dt)
            for b in self.buffers():
                if b is not None and dtype_mod.is_floating_point(b.dtype):
                    b._value = b._value.astype(dt)
            self._dtype = dt
            for l in self.sublayers():
                l._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------ hooks/call
    def register_forward_pre_hook(self, hook):
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self.named_children():
            mod_str = repr(sub)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

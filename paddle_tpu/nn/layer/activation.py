"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _simple(name, fn, **default_kw):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kw = dict(default_kw)
            # positional args map onto the declared defaults in order
            for k, v in zip(default_kw, args):
                kw[k] = v
            for k in default_kw:
                if k in kwargs:
                    kw[k] = kwargs[k]
            self._kw = kw

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU", F.relu)
ReLU6 = _simple("ReLU6", F.relu6)
GELU = _simple("GELU", F.gelu, approximate=False)
Sigmoid = _simple("Sigmoid", F.sigmoid)
Tanh = _simple("Tanh", F.tanh)
LeakyReLU = _simple("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _simple("ELU", F.elu, alpha=1.0)
SELU = _simple("SELU", F.selu)
CELU = _simple("CELU", F.celu, alpha=1.0)
Silu = _simple("Silu", F.silu)
Swish = _simple("Swish", F.swish)
Mish = _simple("Mish", F.mish)
Hardswish = _simple("Hardswish", F.hardswish)
Hardsigmoid = _simple("Hardsigmoid", F.hardsigmoid)
Hardtanh = _simple("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _simple("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _simple("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _simple("Tanhshrink", F.tanhshrink)
Softplus = _simple("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _simple("Softsign", F.softsign)
LogSigmoid = _simple("LogSigmoid", F.log_sigmoid)
ThresholdedReLU = _simple("ThresholdedReLU", F.thresholded_relu, threshold=1.0)
Softmax = _simple("Softmax", F.softmax, axis=-1)
LogSoftmax = _simple("LogSoftmax", F.log_softmax, axis=-1)
Maxout = _simple("Maxout", F.maxout, groups=2, axis=1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)

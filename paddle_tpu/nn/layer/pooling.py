"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class _Pool(Layer):
    def __init__(self, fn, kernel_size=None, stride=None, padding=0, **kw):
        super().__init__()
        self._fn = fn
        self._args = dict(kw)
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return self._fn(x, self.kernel_size, self.stride, self.padding, **self._args)


class MaxPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__(F.max_pool1d, kernel_size, stride, padding)


class MaxPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__(F.max_pool2d, kernel_size, stride, padding,
                         data_format=data_format)


class MaxPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__(F.max_pool3d, kernel_size, stride, padding,
                         data_format=data_format)


class AvgPool1D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__(F.avg_pool1d, kernel_size, stride, padding)


class AvgPool2D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__(F.avg_pool2d, kernel_size, stride, padding,
                         data_format=data_format)


class AvgPool3D(_Pool):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(F.avg_pool3d, kernel_size, stride, padding,
                         data_format=data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size, self._data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._output_size = output_size
        self._data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._output_size, self._data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._output_size)

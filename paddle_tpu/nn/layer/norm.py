"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr, default_initializer=I.Constant(1.0),
        )
        self.bias = self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance", Tensor(np.ones(num_features, np.float32)))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm (acts like BatchNorm1D/2D/3D by input rank)."""


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit the batch axis is sharded and XLA computes
    global batch statistics automatically when the reduction spans the mesh
    (reference: nn/layer/norm.py SyncBatchNorm + sync_batch_norm_op.cu).
    Eager single-process: identical to BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            out.weight.set_value(layer.weight)
            out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0),
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0),
            )

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (
            None if weight_attr is False
            else self.create_parameter([num_channels], attr=weight_attr,
                                       default_initializer=I.Constant(1.0))
        )
        self.bias = (
            None if bias_attr is False
            else self.create_parameter([num_channels], attr=bias_attr, is_bias=True,
                                       default_initializer=I.Constant(0.0))
        )

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter([num_features], attr=weight_attr,
                                               default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True,
                                              default_initializer=I.Constant(0.0))

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta, self.k,
                                     self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm lands with the GAN op family")

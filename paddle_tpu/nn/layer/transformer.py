"""Transformer layers.

Reference: python/paddle/nn/layer/transformer.py (MultiHeadAttention:87,
TransformerEncoderLayer:397, TransformerEncoder:539, TransformerDecoderLayer:617,
TransformerDecoder:788, Transformer:873). Same constructor/forward contracts,
including incremental-decode caches (Cache/StaticCache, gen_cache) and
`normalize_before` pre/post-LN. TPU-native: attention lowers through
F.scaled_dot_product_attention (one fused XLA region) instead of the
fused_attention CUDA op.
"""
from __future__ import annotations

import collections

import numpy as np

from ...framework import dtype as dtype_mod
from .. import functional as F
from .. import initializer as I
from .common import Dropout, Linear
from .layers import Layer
from .norm import LayerNorm


def _convert_attn_mask(mask, dtype):
    """Reference _convert_attention_mask: bool mask → additive float mask."""
    if mask is None:
        return None
    if str(mask.dtype) in ("bool", "uint8"):
        from ... import tensor as ops

        return ops.scale(ops.cast(mask, dtype), 1e4) - 1e4  # True→0, False→-1e4
    return mask


class MultiHeadAttention(Layer):
    """reference transformer.py:87. q/k/v projections + scaled-dot-product.

    Layout matches the reference: inputs [batch, seq, embed_dim]; internally
    [batch, seq, heads, head_dim] with attention over [b,h,q,k].
    """

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim, \
            "embed_dim must be divisible by num_heads"
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        from ... import tensor as ops

        q = self.q_proj(query)
        q = ops.reshape(q, [0, 0, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = ops.reshape(self.k_proj(key), [0, 0, self.num_heads, self.head_dim])
            v = ops.reshape(self.v_proj(value), [0, 0, self.num_heads, self.head_dim])
        if isinstance(cache, self.Cache):
            k = ops.concat([cache.k, k], axis=1)
            v = ops.concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return (q, k, v) if cache is None else (q, k, v, cache)

    def gen_cache(self, key, value=None, type=None):
        """reference transformer.py:279. type=MultiHeadAttention.Cache for
        incremental decode; StaticCache precomputes cross-attention k/v."""
        from ... import tensor as ops

        if type == MultiHeadAttention.StaticCache or (value is not None and type is None):
            value = key if value is None else value
            k = ops.reshape(self.k_proj(key), [0, 0, self.num_heads, self.head_dim])
            v = ops.reshape(self.v_proj(value), [0, 0, self.num_heads, self.head_dim])
            return self.StaticCache(k, v)
        batch = key.shape[0]
        k = ops.zeros([batch, 0, self.num_heads, self.head_dim], dtype=key.dtype)
        return self.Cache(k, ops.zeros_like(k))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from ... import tensor as ops

        key = query if key is None else key
        value = key if value is None else value
        if cache is None:
            q, k, v = self._prepare_qkv(query, key, value, None)
        else:
            q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        mask = _convert_attn_mask(attn_mask, dtype_mod.dtype_name(q.dtype))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout, training=self.training)
        out = ops.reshape(out, [0, 0, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


def _activation(name):
    return getattr(F, name)


class TransformerEncoderLayer(Layer):
    """reference transformer.py:397."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        w = self._pick(weight_attr)
        b = self._pick(bias_attr)
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=w[0], bias_attr=b[0])
        self.linear1 = Linear(d_model, dim_feedforward, w[1], b[1])
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, w[1], b[1])
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = _activation(activation)

    @staticmethod
    def _pick(attr):
        if isinstance(attr, (list, tuple)):
            return list(attr) + [attr[-1]] * (2 - len(attr))
        return [attr, attr]

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


class TransformerEncoder(Layer):
    """reference transformer.py:539."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        from .container import LayerList

        self.layers = LayerList(
            [encoder_layer] +
            [type(encoder_layer)(**_init_args(encoder_layer))
             for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_cache = mod(output, src_mask=src_mask, cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """reference transformer.py:617 (self-attn + cross-attn + FFN)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        w = self._pick(weight_attr)
        b = self._pick(bias_attr)
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=w[0], bias_attr=b[0])
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                             weight_attr=w[1], bias_attr=b[1])
        self.linear1 = Linear(d_model, dim_feedforward, w[2], b[2])
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, w[2], b[2])
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = _activation(activation)

    @staticmethod
    def _pick(attr):
        if isinstance(attr, (list, tuple)):
            return list(attr) + [attr[-1]] * (3 - len(attr))
        return [attr, attr, attr]

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask, None)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, None)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask,
                                                cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache, static_cache))

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(memory,
                                                     type=MultiHeadAttention.Cache)
        static_cache = self.cross_attn.gen_cache(memory, memory,
                                                 type=MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    """reference transformer.py:788."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        from .container import LayerList

        self.layers = LayerList(
            [decoder_layer] +
            [type(decoder_layer)(**_init_args(decoder_layer))
             for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask,
                             memory_mask=memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask=tgt_mask,
                                        memory_mask=memory_mask, cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


def _init_args(layer):
    """Re-construct sibling layers with the same hyperparameters."""
    if isinstance(layer, TransformerEncoderLayer):
        return dict(
            d_model=layer.self_attn.embed_dim, nhead=layer.self_attn.num_heads,
            dim_feedforward=layer.linear1.weight.shape[1],
            dropout=layer.dropout1.p, activation=layer.activation.__name__,
            attn_dropout=layer.self_attn.dropout, act_dropout=layer.dropout.p,
            normalize_before=layer.normalize_before)
    if isinstance(layer, TransformerDecoderLayer):
        return dict(
            d_model=layer.self_attn.embed_dim, nhead=layer.self_attn.num_heads,
            dim_feedforward=layer.linear1.weight.shape[1],
            dropout=layer.dropout1.p, activation=layer.activation.__name__,
            attn_dropout=layer.self_attn.dropout, act_dropout=layer.dropout.p,
            normalize_before=layer.normalize_before)
    raise TypeError(type(layer))


class Transformer(Layer):
    """reference transformer.py:873 — full encoder-decoder."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer, num_encoder_layers,
                                              norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer, num_decoder_layers,
                                              norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    def generate_square_subsequent_mask(self, length):
        """reference transformer.py:1030 — additive causal mask."""
        from ... import tensor as ops

        mask = np.triu(np.full((length, length), -np.inf, dtype="float32"), k=1)
        return ops.to_tensor(mask)

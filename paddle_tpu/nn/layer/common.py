"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import numpy as np

from ...framework import dtype as dtype_mod
from .. import functional as F
from .. import initializer as I
from .layers import Layer


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features]
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self._dtype = dtype_mod.get_default_dtype()
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform(),
        )
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True,
        )
        self.name = name

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.weight.shape[0]}, out_features={self.weight.shape[1]}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, self.p, self.axis, self.training, self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, self.p, self.training, self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, self.p, self.training, self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, self.p, self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        from ...tensor import flatten

        return flatten(input, self.start_axis, self.stop_axis)


class Embedding(Layer):
    """Reference: nn/layer/common.py Embedding; lookup_table_v2 op."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (
            None if padding_idx is None
            else padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
        )
        self._sparse = sparse
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0),
        )
        if self._padding_idx is not None:
            arr = self.weight.numpy().copy()
            arr[self._padding_idx] = 0
            self.weight.set_value(arr)

    def forward(self, x):
        return F.embedding(x, self.weight, self._padding_idx,
                           sparse=self._sparse)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, self._mode, self._value, self._data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self._axis = axis
        self._eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self._axis, self._eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr,
        )
        self.bias = self.create_parameter(shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = upscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._factor, self._data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._p, self._eps, self._keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self._p, self._eps, self._keepdim)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        ks, st, pad, osz, df = self._args
        return F.max_unpool2d(x, indices, ks, st, pad, osz, df)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        ks, st, pad, osz, df = self._args
        return F.max_unpool1d(x, indices, ks, st, pad, osz, df)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        ks, st, pad, osz, df = self._args
        return F.max_unpool3d(x, indices, ks, st, pad, osz, df)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer (reference: nn/layer/loss.py
    HSigmoidLoss over hierarchical_sigmoid_op.cc)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self._num_classes = num_classes
        rows = num_classes if is_custom else max(num_classes - 1, 1)
        self.weight = self.create_parameter([rows, feature_size],
                                            attr=weight_attr)
        self.bias = self.create_parameter([rows], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)

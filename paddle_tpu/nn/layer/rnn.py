"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells, RNN, BiRNN).

Reference: python/paddle/nn/layer/rnn.py (RNNCellBase:~80, SimpleRNNCell,
LSTMCell, GRUCell, RNN:~700 — which lowers to a CUDNN kernel or an
unrolled control-flow graph) over operators/rnn_op.

TPU-native: the recurrence is ONE lax.scan over time per (layer,
direction) — compiled, not unrolled; gate matmuls batch [b, x]@[x, gh]
onto the MXU; variable lengths mask state updates inside the scan (the
reference's sequence_length semantics: states freeze past each sample's
length and padded outputs are zero).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.autograd import call_op
from ...framework.tensor import Tensor
from .. import functional as F
from ..initializer import Uniform
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    """reference rnn.py RNNCellBase: init-state helper + state shape/dtype."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ... import tensor as ops

        b = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape[0], (list, tuple)):
            return tuple(
                ops.full([b] + list(s), init_value, dtype or "float32")
                for s in shape)
        return ops.full([b] + list(shape), init_value, dtype or "float32")


def _cell_params(layer, input_size, hidden_size, gates, recurrent_size=None):
    std = 1.0 / math.sqrt(hidden_size)
    init = Uniform(-std, std)
    layer.weight_ih = layer.create_parameter(
        shape=[gates * hidden_size, input_size], default_initializer=init)
    layer.weight_hh = layer.create_parameter(
        shape=[gates * hidden_size, recurrent_size or hidden_size],
        default_initializer=init)
    layer.bias_ih = layer.create_parameter(
        shape=[gates * hidden_size], is_bias=True, default_initializer=init)
    layer.bias_hh = layer.create_parameter(
        shape=[gates * hidden_size], is_bias=True, default_initializer=init)


def _simple_step(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jnp.tanh(z) if activation == "tanh" else jax.nn.relu(z)


def _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    z = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c_new = f * c + i * jnp.tanh(g)
    return o * jnp.tanh(c_new), c_new


def _gru_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xi = x @ w_ih.T + b_ih
    hi = h @ w_hh.T + b_hh
    xr, xz, xn = jnp.split(xi, 3, axis=-1)
    hr, hz, hn = jnp.split(hi, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1 - z) * n + z * h


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self.activation
        out = call_op(
            lambda x, h, wi, wh, bi, bh: _simple_step(x, h, wi, wh, bi, bh,
                                                      act),
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh, op_name="simple_rnn_cell")
        return out, out


class LSTMCell(RNNCellBase):
    """LSTM cell; proj_size > 0 adds the recurrent projection of the
    reference lstmp op (operators/lstmp_op.cc — Sak et al. LSTMP): the
    emitted/recurrent hidden state is h @ W_proj of size proj_size while
    the cell state stays hidden_size."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.proj_size = int(proj_size)
        _cell_params(self, input_size, hidden_size, 4,
                     recurrent_size=self.proj_size or None)
        if self.proj_size:
            std = 1.0 / math.sqrt(hidden_size)
            self.weight_proj = self.create_parameter(
                shape=[self.proj_size, hidden_size],
                default_initializer=Uniform(-std, std))

    @property
    def state_shape(self):
        h = self.proj_size or self.hidden_size
        return ((h,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        if self.proj_size:
            out = call_op(
                lambda x, hv, cv, wi, wh, bi, bh, wp: (
                    lambda hc: (hc[0] @ wp.T, hc[1])
                )(_lstm_step(x, hv, cv, wi, wh, bi, bh)),
                inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
                self.bias_hh, self.weight_proj, op_name="lstmp_cell")
        else:
            out = call_op(
                lambda x, hv, cv, wi, wh, bi, bh: _lstm_step(x, hv, cv, wi,
                                                             wh, bi, bh),
                inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
                self.bias_hh, op_name="lstm_cell")
        h_new, c_new = out
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        _cell_params(self, input_size, hidden_size, 3)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = call_op(
            lambda x, h, wi, wh, bi, bh: _gru_step(x, h, wi, wh, bi, bh),
            inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
            self.bias_hh, op_name="gru_cell")
        return out, out


def _scan_layer(mode, xs, h0, c0, params, reverse, lengths, activation):
    """One (layer, direction) recurrence as a lax.scan. xs: [t, b, x]."""
    w_ih, w_hh, b_ih, b_hh = params
    T = xs.shape[0]
    t_idx = jnp.arange(T)
    if reverse:
        xs = xs[::-1]
        t_idx = t_idx[::-1]

    def step(carry, inp):
        x_t, t = inp
        if mode == "LSTM":
            h, c = carry
            h_new, c_new = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
        elif mode == "GRU":
            h = carry
            h_new = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
            c_new = None
        else:
            h = carry
            h_new = _simple_step(x_t, h, w_ih, w_hh, b_ih, b_hh, activation)
            c_new = None
        if lengths is not None:
            valid = (t < lengths)[:, None]
            if mode == "LSTM":
                h_new = jnp.where(valid, h_new, h)
                c_new = jnp.where(valid, c_new, c)
            else:
                h_new = jnp.where(valid, h_new, h)
            out_t = jnp.where(valid, h_new, 0.0)
        else:
            out_t = h_new
        new_carry = (h_new, c_new) if mode == "LSTM" else h_new
        return new_carry, out_t

    init = (h0, c0) if mode == "LSTM" else h0
    carry, outs = jax.lax.scan(step, init, (xs, t_idx))
    if reverse:
        outs = outs[::-1]
    if mode == "LSTM":
        return outs, carry[0], carry[1]
    return outs, carry, carry


class _RNNBase(Layer):
    """Multi-layer (bi)directional stack (reference rnn.py SimpleRNN/LSTM/
    GRU shared machinery)."""

    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        gates = {"LSTM": 4, "GRU": 3, "RNN": 1}[self.MODE]
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self._params = []
        for layer_i in range(num_layers):
            for d in range(self.num_directions):
                in_sz = (input_size if layer_i == 0
                         else hidden_size * self.num_directions)
                names = []
                for pname, shape, bias in (
                        ("weight_ih", [gates * hidden_size, in_sz], False),
                        ("weight_hh", [gates * hidden_size, hidden_size],
                         False),
                        ("bias_ih", [gates * hidden_size], True),
                        ("bias_hh", [gates * hidden_size], True)):
                    suffix = f"_l{layer_i}" + ("_reverse" if d else "")
                    p = self.create_parameter(
                        shape=shape, is_bias=bias, default_initializer=init)
                    setattr(self, pname + suffix, p)
                    names.append(pname + suffix)
                self._params.append(names)

    def _param_tensors(self):
        return [[getattr(self, n) for n in group] for group in self._params]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import tensor as ops

        x = inputs if self.time_major else ops.transpose(inputs, [1, 0, 2])
        T, B = x.shape[0], x.shape[1]
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        mode = self.MODE

        if initial_states is None:
            h0 = ops.zeros([L * D, B, H], dtype="float32")
            c0 = ops.zeros([L * D, B, H], dtype="float32") \
                if mode == "LSTM" else None
        elif mode == "LSTM":
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, None

        groups = self._param_tensors()
        flat_params = [p for g in groups for p in g]
        n_per = 4
        act = self.activation
        lengths_t = sequence_length

        def fn(xv, h0v, *rest):
            if mode == "LSTM":
                c0v = rest[0]
                rest = rest[1:]
            else:
                c0v = None
            if lengths_t is not None:
                lens = rest[0]
                rest = rest[1:]
            else:
                lens = None
            pvals = [rest[i * n_per:(i + 1) * n_per]
                     for i in range(L * D)]
            cur = xv
            h_finals, c_finals = [], []
            for li in range(L):
                outs_dirs = []
                for d in range(D):
                    gi = li * D + d
                    outs, hf, cf = _scan_layer(
                        mode, cur, h0v[gi], c0v[gi] if c0v is not None
                        else None, pvals[gi], reverse=bool(d),
                        lengths=lens, activation=act)
                    outs_dirs.append(outs)
                    h_finals.append(hf)
                    c_finals.append(cf)
                cur = (outs_dirs[0] if D == 1
                       else jnp.concatenate(outs_dirs, axis=-1))
            h_fin = jnp.stack(h_finals)
            if mode == "LSTM":
                return cur, h_fin, jnp.stack(c_finals)
            return cur, h_fin

        args = [x, h0]
        if mode == "LSTM":
            args.append(c0)
        if lengths_t is not None:
            args.append(lengths_t)
        args += flat_params
        out = call_op(fn, *args, op_name=f"{mode.lower()}_stack")
        if mode == "LSTM":
            y, hf, cf = out
            states = (hf, cf)
        else:
            y, hf = out
            states = hf
        if not self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, states


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Generic scan wrapper over a user cell (reference rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import tensor as ops

        x = inputs if self.time_major else ops.transpose(inputs, [1, 0, 2])
        T = x.shape[0]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = []
        for t in steps:
            o, states = self.cell(x[t], states)
            outs.append(o)
        if self.is_reverse:
            outs = outs[::-1]
        y = ops.stack(outs, axis=0)
        if not self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import tensor as ops

        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        y_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return ops.concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)

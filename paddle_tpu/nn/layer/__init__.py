from .layers import Layer  # noqa: F401
from . import activation, common, container, conv, loss, norm, pooling  # noqa: F401

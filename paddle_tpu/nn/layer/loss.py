"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index, self.reduction,
                               self.soft_label, self.axis, self.use_softmax,
                               self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index, self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction,
                                                  self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, fu, ep, red = self._args
        return F.poisson_nll_loss(input, label, li, fu, ep, red)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        fu, ep, red = self._args
        return F.gaussian_nll_loss(input, label, variance, fu, ep, red)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, reduction)
        self._weight = weight

    def forward(self, input, label):
        p, m, red = self._args
        return F.multi_margin_loss(input, label, p, m, self._weight, red)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax (reference nn.AdaptiveLogSoftmaxWithLoss; Grave et
    al.): vocab split by `cutoffs` into a head + shrinking-projection tail
    clusters, so frequent-word logits cost a small matmul.

    forward(input [N, F], label [N]) -> (target log-probs [N], mean loss).
    """

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        import jax.numpy as jnp

        cutoffs = list(cutoffs)
        if (not cutoffs or cutoffs != sorted(set(cutoffs))
                or cutoffs[-1] >= n_classes):
            raise ValueError("cutoffs must be increasing and < n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter(
            shape=[in_features, self.head_size])
        self.head_bias = self.create_parameter(
            shape=[self.head_size], is_bias=True) if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter(shape=[in_features, hsz])
            w2 = self.create_parameter(shape=[hsz, osz])
            setattr(self, f"tail_proj_{i}", w1)
            setattr(self, f"tail_out_{i}", w2)
            self.tail_weights.append((f"tail_proj_{i}", f"tail_out_{i}"))

    def _log_probs(self, input):
        """Full [N, n_classes] log-probs composed from head + tails."""
        import jax
        import jax.numpy as jnp

        from ...framework.autograd import call_op

        params = [self.head_weight]
        if self.head_bias is not None:
            params.append(self.head_bias)
        for p1, p2 in self.tail_weights:
            params.append(getattr(self, p1))
            params.append(getattr(self, p2))
        n_clusters = self.n_clusters
        cutoffs = self.cutoffs
        has_bias = self.head_bias is not None

        def fn(x, *ws):
            idx = 0
            hw = ws[idx]; idx += 1
            head = x @ hw
            if has_bias:
                head = head + ws[idx]; idx += 1
            head_lp = jax.nn.log_softmax(head, axis=-1)
            pieces = [head_lp[:, :cutoffs[0]]]
            for i in range(n_clusters):
                w1, w2 = ws[idx], ws[idx + 1]; idx += 2
                tail_lp = jax.nn.log_softmax((x @ w1) @ w2, axis=-1)
                gate = head_lp[:, cutoffs[0] + i][:, None]
                pieces.append(gate + tail_lp)
            return jnp.concatenate(pieces, axis=-1)

        return call_op(fn, input, *params, op_name="adaptive_log_softmax")

    def forward(self, input, label):
        from ... import tensor as ops
        from ...framework.autograd import call_op
        import jax.numpy as jnp

        lp = self._log_probs(input)
        out = call_op(
            lambda l, y: jnp.take_along_axis(
                l, y.reshape(-1, 1).astype(jnp.int32), axis=1)[:, 0],
            lp, label, op_name="adaptive_pick")
        loss = ops.mean(-out)
        return out, loss

    def log_prob(self, input):
        return self._log_probs(input)

    def predict(self, input):
        from ... import tensor as ops

        return ops.argmax(self._log_probs(input), axis=-1)

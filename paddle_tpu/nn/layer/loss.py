"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index, self.reduction,
                               self.soft_label, self.axis, self.use_softmax,
                               self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self._weight, self._ignore_index, self._reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction,
                                                  self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self._reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self._args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, fu, ep, red = self._args
        return F.poisson_nll_loss(input, label, li, fu, ep, red)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self._args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        fu, ep, red = self._args
        return F.gaussian_nll_loss(input, label, variance, fu, ep, red)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self._args = (p, margin, reduction)
        self._weight = weight

    def forward(self, input, label):
        p, m, red = self._args
        return F.multi_margin_loss(input, label, p, m, self._weight, red)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self._blank, self._reduction, norm_by_times)

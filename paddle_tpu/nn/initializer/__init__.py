"""Weight initializers (reference: python/paddle/fluid/initializer.py,
python/paddle/nn/initializer/).

Each initializer is callable on a Parameter and overwrites its value using the
global seeded PRNG stream.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import next_key
from ...framework.tensor import Tensor


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        param._value = jnp.full(param._value.shape, self.value, param._value.dtype)
        return param


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        sample_dt = dt if jnp.issubdtype(dt, jnp.floating) else jnp.float32
        param._value = jax.random.uniform(
            next_key(), shape, sample_dt, minval=self.low, maxval=self.high
        ).astype(dt)
        return param


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        param._value = (
            jax.random.normal(next_key(), shape, jnp.float32) * self.std + self.mean
        ).astype(dt)
        return param


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        param._value = (
            jax.random.truncated_normal(next_key(), -2.0, 2.0, shape, jnp.float32) * self.std
            + self.mean
        ).astype(dt)
        return param


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight is [in, out]
        return shape[0], shape[1]
    # conv weight [out_c, in_c/groups, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        param._value = jax.random.uniform(
            next_key(), shape, jnp.float32, minval=-limit, maxval=limit
        ).astype(dt)
        return param


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        param._value = (jax.random.normal(next_key(), shape, jnp.float32) * std).astype(dt)
        return param


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        param._value = jax.random.uniform(
            next_key(), shape, jnp.float32, minval=-limit, maxval=limit
        ).astype(dt)
        return param


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="leaky_relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        param._value = (jax.random.normal(next_key(), shape, jnp.float32) * std).astype(dt)
        return param


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(self.value)
        param._value = v.astype(param._value.dtype).reshape(param._value.shape)
        return param


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        param._value = jax.nn.initializers.orthogonal(self.gain)(
            next_key(), shape, jnp.float32
        ).astype(dt)
        return param


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, param, block=None):
        shape, dt = param._value.shape, param._value.dtype
        arr = np.zeros(shape, dtype=np.float32)
        out_c, in_c = shape[0], shape[1]
        centers = [k // 2 for k in shape[2:]]
        for i in range(min(out_c, in_c * self.groups)):
            idx = (i, i % in_c) + tuple(centers)
            arr[idx] = 1.0
        param._value = jnp.asarray(arr).astype(dt)
        return param


def calculate_gain(nonlinearity, param=None):
    recommended = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in recommended:
        raise ValueError(f"Unknown nonlinearity {nonlinearity}")
    return recommended[nonlinearity]


_GLOBAL = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """paddle.nn.initializer.set_global_initializer."""
    _GLOBAL["weight"] = weight_init
    _GLOBAL["bias"] = bias_init


def global_initializer(is_bias):
    return _GLOBAL["bias" if is_bias else "weight"]


class Bilinear(Initializer):
    """Bilinear-interpolation kernel init for transposed-conv upsampling
    (reference initializer.py BilinearInitializer)."""

    def __call__(self, param, block=None):
        shape = param._value.shape
        if len(shape) != 4:
            raise ValueError("Bilinear init expects a 4-D conv weight")
        kh, kw = shape[2], shape[3]
        fh, fw = (kh + 1) // 2, (kw + 1) // 2
        # separable triangle filter centered per factor parity
        def tri(k, f):
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            x = np.arange(k)
            return 1 - np.abs(x / f - c)

        filt = np.outer(tri(kh, fh), tri(kw, fw)).astype(np.float32)
        arr = np.zeros(shape, np.float32)
        for i in range(min(shape[0], shape[1])):
            arr[i, i] = filt
        param.set_value(arr)

"""paddle.nn.utils — weight_norm / spectral_norm / parameter vector utils.

Reference: python/paddle/nn/utils/{weight_norm_hook.py,spectral_norm_hook.py,
transform_parameters.py}.

TPU-native: reparameterizations recompute the effective weight inside the
layer's forward (a fused elementwise+matmul for XLA) instead of the
reference's pre-forward hook mutation.
"""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor
from ... import tensor as ops

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(w, dim):
    import jax.numpy as jnp

    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v / ||v|| (weight_norm_hook.py).
    The effective weight is recomputed on every forward."""
    import jax.numpy as jnp

    from ...framework.autograd import call_op

    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    wv = w._value
    g0 = np.asarray(_norm_except(wv, dim))
    v = layer.create_parameter(shape=list(wv.shape))
    v.set_value(np.asarray(wv))
    g = layer.create_parameter(shape=list(g0.shape))
    g.set_value(g0)
    setattr(layer, name + "_v", v)
    setattr(layer, name + "_g", g)
    # drop the original parameter from the layer's registry
    if name in layer._parameters:
        del layer._parameters[name]

    orig_forward = layer.forward

    def forward(*args, **kwargs):
        eff = call_op(
            lambda vv, gg: vv * (gg / jnp.maximum(
                _norm_except(vv, dim), 1e-12)),
            v, g, op_name="weight_norm")
        object.__setattr__(layer, name, eff)
        return orig_forward(*args, **kwargs)

    layer.forward = forward
    layer._weight_norm_state = (name, dim, orig_forward)
    return layer


def remove_weight_norm(layer, name="weight"):
    state = getattr(layer, "_weight_norm_state", None)
    if state is None:
        return layer
    pname, dim, orig_forward = state
    import jax.numpy as jnp

    v = getattr(layer, pname + "_v")
    g = getattr(layer, pname + "_g")
    eff = np.asarray(v._value * (g._value / np.maximum(
        np.asarray(_norm_except(v._value, dim)), 1e-12)))
    w = layer.create_parameter(shape=list(eff.shape))
    w.set_value(eff)
    setattr(layer, pname, w)
    del layer._parameters[pname + "_v"]
    del layer._parameters[pname + "_g"]
    layer.forward = orig_forward
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Reparameterize layer.<name> as W / sigma(W) with power-iteration
    sigma (spectral_norm_hook.py)."""
    import jax.numpy as jnp

    from ...framework.autograd import call_op

    w = getattr(layer, name)
    wv = np.asarray(w._value)
    if dim is None:
        dim = 0
    mat = np.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    rs = np.random.RandomState(0)
    u = rs.randn(mat.shape[0]).astype("float32")
    u /= np.linalg.norm(u) + eps
    layer._sn_u = u

    orig_forward = layer.forward
    orig_param = w

    def forward(*args, **kwargs):
        wv_ = orig_param._value
        m = jnp.moveaxis(wv_, dim, 0).reshape(wv_.shape[dim], -1)
        u_ = jnp.asarray(layer._sn_u)
        for _ in range(n_power_iterations):
            v_ = m.T @ u_
            v_ = v_ / (jnp.linalg.norm(v_) + eps)
            u_ = m @ v_
            u_ = u_ / (jnp.linalg.norm(u_) + eps)
        layer._sn_u = np.asarray(u_)
        sigma = u_ @ m @ v_

        eff = call_op(lambda W: W / sigma, orig_param,
                      op_name="spectral_norm")
        object.__setattr__(layer, name, eff)
        return orig_forward(*args, **kwargs)

    layer.forward = forward
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one vector (transform_parameters.py)."""
    from ...framework.autograd import call_op

    params = list(parameters)

    def fn(*vals):
        import jax.numpy as jnp

        return jnp.concatenate([v.reshape(-1) for v in vals])

    return call_op(fn, *params, op_name="parameters_to_vector")


def vector_to_parameters(vec, parameters, name=None):
    params = list(parameters)
    flat = np.asarray(vec.numpy() if isinstance(vec, Tensor) else vec)
    pos = 0
    for p in params:
        n = int(np.prod(p.shape))
        p.set_value(flat[pos:pos + n].reshape(tuple(p.shape)))
        pos += n
    return params


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global grad-norm clip (reference nn/utils/clip_grad_norm_)."""
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(np.zeros(()))
    import jax.numpy as jnp

    norms = [jnp.linalg.norm(jnp.ravel(p.grad._value)) for p in params]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(norms))
    else:
        total = jnp.sum(jnp.stack(norms) ** norm_type) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite gradient norm")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        p.grad._value = p.grad._value * scale
    return Tensor(total, _internal=True)


def clip_grad_value_(parameters, clip_value):
    import jax.numpy as jnp

    for p in (parameters if isinstance(parameters, (list, tuple))
              else [parameters]):
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)

"""paddle.sysconfig (parity: python/paddle/sysconfig.py)."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "core",
                        "csrc")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "core",
                        "_lib")

"""paddle_tpu.inference — the deployment/serving runtime.

Parity surface: paddle.inference (Config, create_predictor, Predictor with
zero-copy input/output handles) whose engine is AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:87: load model → run
optimization passes → zero-copy execution).

TPU-native engine: the saved artifact is already a compiled-form StableHLO
function (inference/io.py); "analysis passes" are XLA's compile at load,
weights are placed on device once, and handles move data without extra
copies (jnp.asarray adopts host buffers where dlpack allows).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .io import InferenceArtifact, export_inference_artifact  # noqa: F401

_compat_warned: set = set()


def _warn_compat_once(knob: str, why: str):
    """CUDA/oneDNN-era Config knobs are kept for API parity but cannot
    select anything here — say so once instead of silently no-oping."""
    from ..utils.compat import warn_compat_once

    warn_compat_once(_compat_warned, "inference.Config.", knob, why,
                     stacklevel=4)


__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType"]


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    TPU = "tpu"


class Config:
    """paddle.inference.Config (analysis_config.h surface)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = None
        self._enable_memory_optim = True
        self._ir_optim = True
        self._int8_weights = False

    # -- model paths --------------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    # -- device knobs (XLA owns placement; recorded for API parity) ---------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        _warn_compat_once(
            "enable_use_gpu", "device placement follows the ambient jax "
            "platform (TPU/CPU); the GPU memory-pool knobs do nothing here")
        self._device = "gpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "gpu"

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_int8_weights(self, flag=True):
        """Weight-only int8 at load (ISSUE 13): every 2-D float weight of
        the model is quantized through the pallas ``quantize_int8`` kernel
        (per-output-channel scales, name-derived deterministic seeds) and
        held int8 at rest — half the weight HBM, the memory-bound serving
        win — then dequantized per run inside the compiled program.
        Activations and 1-D tensors (biases, norms) stay float. Layer
        models get the same opt-in via quantization.convert_to_int8,
        whose matmuls ride the tuner-dispatched quant_matmul kernel.
        Supported for reference-format (imported) models; native StableHLO
        artifacts bake their weights into the saved program."""
        self._int8_weights = bool(flag)

    def int8_weights(self) -> bool:
        return self._int8_weights

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def ir_optim(self):
        return self._ir_optim

    def enable_mkldnn(self):
        _warn_compat_once(
            "enable_mkldnn", "XLA:CPU is the CPU backend; there is no "
            "oneDNN pass pipeline to enable")

    def set_cpu_math_library_num_threads(self, n):
        _warn_compat_once(
            "set_cpu_math_library_num_threads", "XLA's thread pool is "
            "sized by the runtime; this knob does nothing here")

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT is CUDA-only; on TPU the XLA compile at load time is "
            "the optimizing engine")

    def summary(self):
        return {"model": self.prog_file(), "params": self.params_file(),
                "device": self._device or "auto"}


class Tensor:
    """Zero-copy input/output handle (paddle_tensor.h ZeroCopyTensor)."""

    def __init__(self, name: str, spec=None):
        self.name = name
        self._spec = spec  # (shape, dtype) for inputs
        self._value = None  # device array

    def copy_from_cpu(self, data: np.ndarray):
        import jax.numpy as jnp

        self._value = jnp.asarray(data)

    def share_external_data(self, data):
        import jax.numpy as jnp

        self._value = jnp.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    @property
    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._spec[0]) if self._spec else None

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)


class _ImportedProgramArtifact:
    """Adapter presenting a reference-format program (interop importer)
    through the InferenceArtifact surface — the whole imported op list is
    jitted into ONE XLA program, so serving an imported reference model
    costs the same as serving a native artifact."""

    def __init__(self, prog, int8_weights=False):
        import jax
        import jax.numpy as jnp
        import numpy as _np

        from ..interop.importer import _run_op

        self.feed_names = list(prog.feed_names)
        b0 = prog.blocks[0]
        self.feed_specs = {}
        for n in self.feed_names:
            var = b0.vars.get(n)
            self.feed_specs[n] = ((var.shape, var.dtype)
                                  if var is not None else (None, None))
        self.n_fetches = len(prog.fetch_names)
        # weights ride as a jit ARGUMENT (device arrays held once) — closing
        # over them would bake every weight into the executable as literal
        # constants, re-embedded on each input-shape retrace
        self._params = {k: jnp.asarray(v) for k, v in prog.params.items()}
        self._int8_dtypes = {}
        if int8_weights:
            # weight-only int8 at rest (Config.enable_int8_weights): every
            # 2-D float weight becomes (int8 payload, per-channel scales)
            # via the pallas quantize kernel under a name-derived
            # deterministic seed; the compiled program dequantizes per run
            from ..ops.quant_matmul import quantize_int8, stable_seed

            for name in sorted(self._params):
                v = self._params[name]
                if v.ndim != 2 or not _np.issubdtype(
                        _np.dtype(v.dtype), _np.floating):
                    continue
                q, s = quantize_int8(v.astype(jnp.float32),
                                     seed=stable_seed(name))
                self._int8_dtypes[name] = v.dtype
                self._params[name] = (q, s)
        int8_dtypes = dict(self._int8_dtypes)
        ops, fetches = b0.ops, list(prog.fetch_names)

        def fn(params, feed):
            V = {}
            for k, v in params.items():
                # tuple check (not name check): export_native re-traces
                # this fn with already-dequantized plain float weights
                if k in int8_dtypes and isinstance(v, tuple):
                    q, s = v
                    V[k] = (q.astype(jnp.float32) * s).astype(
                        int8_dtypes[k])
                else:
                    V[k] = v
            V.update(feed)
            for op in ops:
                _run_op(op, V, jnp)
            return [V[n] for n in fetches]

        self._fn = jax.jit(fn)

    def run(self, feed_vals):
        return self._fn(self._params, dict(zip(self.feed_names, feed_vals)))

    def export_native(self, path_prefix: str):
        """Write this imported program as the NATIVE artifact triple
        (serialized StableHLO + weights npz + manifest): subsequent
        create_predictor loads skip the reference-format import, the
        analysis passes, and tracing entirely. The compiled-form half of
        AnalysisPredictor::SaveOptimModel (analysis_predictor.h:265)."""
        from .io import export_inference_artifact

        import jax.numpy as jnp

        pnames = sorted(self._params)
        # int8-at-rest weights export dequantized: the native artifact
        # format carries plain float weights
        pvals = []
        for n in pnames:
            v = self._params[n]
            if n in self._int8_dtypes:
                q, s = v
                v = (q.astype(jnp.float32) * s).astype(
                    self._int8_dtypes[n])
            pvals.append(v)
        feed_specs = []
        for n in self.feed_names:
            shape, dtype = self.feed_specs.get(n, (None, None))
            if shape is None or dtype is None:
                raise ValueError(
                    f"feed {n!r} has no shape/dtype in the imported "
                    f"program — cannot export a typed native artifact")
            feed_specs.append((n, list(shape), dtype))
        run_fn = self._fn  # jit(fn(params_dict, feed_dict))

        def flat_fn(ws, fs):
            return run_fn(dict(zip(pnames, ws)),
                          dict(zip(self.feed_names, fs)))

        return export_inference_artifact(flat_fn, pvals, feed_specs,
                                         path_prefix)


def _load_artifact(prefix: str, params_file: Optional[str] = None,
                   ir_optim: bool = True, int8_weights: bool = False):
    """Native StableHLO artifact (manifest.json present), or a
    reference-format model (dir with __model__, or a .pdmodel ProgramDesc
    protobuf + .pdiparams persistables) via the interop importer. Imported
    programs run the analysis pass stack when ir_optim is on."""
    import os

    from ..interop import load_paddle_inference_model

    def imported(prog):
        if ir_optim:
            from .passes import run_inference_passes

            run_inference_passes(prog)
        return _ImportedProgramArtifact(prog, int8_weights=int8_weights)

    if os.path.exists(prefix + ".manifest.json"):
        if int8_weights:
            import warnings

            warnings.warn(
                "inference.Config.enable_int8_weights: a native StableHLO "
                "artifact bakes its weights into the saved program — int8 "
                "at-rest applies to reference-format (imported) models; "
                "loading this artifact full-precision", stacklevel=3)
        return InferenceArtifact.load(prefix)
    if os.path.isdir(prefix) and \
            os.path.exists(os.path.join(prefix, "__model__")):
        # honor a caller-set combined-params filename (a supported
        # reference layout) before probing the conventional '__params__';
        # a set-but-missing params_file is a config error, not a silent
        # fallback to stale '__params__'/per-var weights
        params = None
        if params_file is not None:
            # the as-given path wins: absolute as-is, relative resolved
            # against the MODEL DIR (not cwd — weight loading must not
            # depend on the launch directory); only then fall back to a
            # basename probe (a path from the original save tree whose
            # blob now sits in the model dir)
            cand = (params_file if os.path.isabs(params_file)
                    else os.path.join(prefix, params_file))
            base = os.path.basename(params_file)
            if os.path.exists(cand):
                params = os.path.relpath(cand, prefix)
            elif os.path.exists(os.path.join(prefix, base)):
                params = base
            else:
                raise FileNotFoundError(
                    f"params file {params_file!r} not found (looked for "
                    f"{cand!r} and {os.path.join(prefix, base)!r})")
        if params is None and os.path.exists(
                os.path.join(prefix, "__params__")):
            params = "__params__"
        return imported(
            load_paddle_inference_model(prefix, params_filename=params))
    if os.path.exists(prefix + ".pdmodel"):
        dirname = os.path.dirname(prefix) or "."
        if params_file is None and os.path.exists(prefix + ".pdiparams"):
            params_file = prefix + ".pdiparams"
        # load_paddle_inference_model falls back to per-var files (and
        # raises a named error) when no combined params blob exists
        return imported(load_paddle_inference_model(
            dirname, model_filename=os.path.basename(prefix) + ".pdmodel",
            params_filename=(os.path.relpath(params_file, dirname)
                             if params_file else None)))
    raise FileNotFoundError(
        f"no inference artifact at {prefix!r} (native .pdmodel+manifest, "
        f"reference __model__ dir, or reference .pdmodel protobuf)")


class Predictor:
    """paddle.inference.Predictor over a loaded StableHLO artifact, or a
    reference-format model imported on the fly (interop importer)."""

    def __init__(self, config: Config):
        if not config._prefix:
            raise ValueError("Config has no model path (set_model)")
        self._artifact = _load_artifact(
            config._prefix, getattr(config, "_params_file", None),
            ir_optim=config.ir_optim(),
            int8_weights=getattr(config, "_int8_weights", False))
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, self._artifact.feed_specs[n])
            for n in self._artifact.feed_names
        }
        self._outputs: List[Tensor] = [
            Tensor(f"fetch_{i}") for i in range(self._artifact.n_fetches)
        ]

    def get_input_names(self) -> List[str]:
        return list(self._artifact.feed_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def get_output_names(self) -> List[str]:
        return [t.name for t in self._outputs]

    def get_output_handle(self, name: str) -> Tensor:
        for t in self._outputs:
            if t.name == name:
                return t
        raise KeyError(name)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. With `inputs` given (list in input-name order), returns
        the outputs directly (the newer paddle.inference convenience); with
        handles, reads staged input buffers and fills output handles.

        The two staging styles do not mix: values staged by a
        ``run(inputs=...)`` call are transient to THAT call and cleared
        afterwards (they overwrite any handle-staged value on the way
        in), so a later handle-style ``run()`` that forgot to re-stage
        raises "input was not set" instead of silently reusing the
        previous convenience-call's arrays."""
        if inputs is not None:
            for n, v in zip(self._artifact.feed_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(v))
        try:
            feed_vals = []
            for n in self._artifact.feed_names:
                h = self._inputs[n]
                if h._value is None:
                    raise RuntimeError(f"input {n!r} was not set")
                feed_vals.append(h._value)
            outs = self._artifact.run(feed_vals)
        finally:
            if inputs is not None:
                for n in self._artifact.feed_names:
                    self._inputs[n]._value = None
        for h, v in zip(self._outputs, outs):
            h._value = v
        if inputs is not None:
            return [np.asarray(v) for v in outs]
        return None

    def clone(self):
        new = object.__new__(Predictor)
        new._artifact = self._artifact  # weights shared (zero-copy clone)
        new._inputs = {n: Tensor(n, self._artifact.feed_specs[n])
                       for n in self._artifact.feed_names}
        new._outputs = [Tensor(f"fetch_{i}")
                        for i in range(self._artifact.n_fetches)]
        return new

    def save_optimized_model(self, path_prefix: str) -> str:
        """AnalysisPredictor::SaveOptimModel (analysis_predictor.h:265):
        persist the post-analysis model so future loads skip the work.

        A reference-format model (imported + analysis passes) is written
        as the native artifact triple (serialized StableHLO + weights +
        manifest); a native artifact is re-saved as-is. Returns the
        .pdmodel path."""
        art = self._artifact
        if isinstance(art, InferenceArtifact):
            return art.save(path_prefix)
        return art.export_native(path_prefix)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)

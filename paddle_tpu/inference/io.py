"""Inference-model serialization format.

Reference: paddle.static.save/load_inference_model
(python/paddle/static/io.py) producing __model__ (ProgramDesc) + params; the
runtime that consumes them is the 59k-LoC AnalysisPredictor stack
(paddle/fluid/inference/api/analysis_predictor.h:87 — load, optimize,
zero-copy run).

TPU-native format: the compiled artifact is a serialized jax.export
StableHLO function  fn(weights..., feeds...) -> fetches  plus a weights blob
and a JSON manifest. "Optimization passes" are XLA's job at load time; the
predictor's zero-copy contract is device-resident weights placed once and
feed/fetch buffers exchanged without host round-trips.

Files written for prefix P:
  P.pdmodel     — serialized StableHLO (jax.export blob)
  P.pdiparams   — npz of weight arrays (w0..wN in call order)
  P.manifest.json — feed names/shapes/dtypes, fetch count, format version
"""
from __future__ import annotations

import io
import json
import os
from typing import List, Sequence

import numpy as np

FORMAT_VERSION = 1


def _write_triple(serialized: bytes, weight_vals: Sequence, manifest: dict,
                  path_prefix: str) -> str:
    """The on-disk format, in ONE place: .pdmodel StableHLO blob +
    .pdiparams npz (w{i} in call order) + .manifest.json."""
    os.makedirs(os.path.dirname(os.path.abspath(path_prefix)) or ".",
                exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(serialized)
    buf = io.BytesIO()
    np.savez(buf, **{f"w{i}": np.asarray(w)
                     for i, w in enumerate(weight_vals)})
    with open(path_prefix + ".pdiparams", "wb") as f:
        f.write(buf.getvalue())
    with open(path_prefix + ".manifest.json", "w") as f:
        json.dump(manifest, f, indent=2)
    return path_prefix + ".pdmodel"


def export_inference_artifact(fn, weight_vals: Sequence, feed_specs,
                              path_prefix: str):
    """Export fn(weights_list, feeds_list) -> fetches and write the triple.

    feed_specs: list of (name, shape, dtype-str).
    """
    import jax

    from ..jit.artifact_cache import require_export

    # jax.export is a LAZY submodule: attribute access off a bare
    # `import jax` raises in a fresh process (the bug that made every
    # artifact load/export look unsupported). require_export() imports
    # it through the capability probe.
    export = require_export()
    w_avals = [jax.ShapeDtypeStruct(np.shape(w), np.asarray(w).dtype)
               for w in weight_vals]
    # None / -1 feed dims export as SYMBOLIC dims (shape polymorphism): the
    # served model accepts any batch size, like the reference's -1 dims.
    # All LEADING dynamic dims share ONE symbol: multi-feed models (ids +
    # mask, image + shape-info) combine their feeds along batch, and
    # independent symbols would make that combination inconclusive at
    # trace time. Non-leading dynamic dims stay independent.
    scope = export.SymbolicScope()
    f_avals = []
    sym_count = 0
    for _, s, d in feed_specs:
        parts = []
        any_sym = False
        for i, dim in enumerate(s):
            if dim is None or (isinstance(dim, int) and dim < 0):
                any_sym = True
                if i == 0:
                    parts.append("batch")
                else:
                    parts.append(f"d{sym_count}")
                    sym_count += 1
            else:
                parts.append(str(int(dim)))
        if any_sym:
            shape = export.symbolic_shape(
                ", ".join(parts), scope=scope)
        else:
            shape = tuple(int(x) for x in s)
        f_avals.append(jax.ShapeDtypeStruct(shape, np.dtype(d)))

    def flat(*args):
        ws = list(args[:len(w_avals)])
        fs = list(args[len(w_avals):])
        return fn(ws, fs)

    # export for both platforms: train-on-TPU / serve-anywhere (and vice
    # versa) is the deployment contract
    exported = export.export(
        jax.jit(flat), platforms=("cpu", "tpu"))(*w_avals, *f_avals)
    manifest = {
        "format": "paddle_tpu_inference",
        "version": FORMAT_VERSION,
        "n_weights": len(w_avals),
        "feeds": [{"name": n, "shape": list(s), "dtype": str(d)}
                  for n, s, d in feed_specs],
        "n_fetches": len(exported.out_avals),
    }
    return _write_triple(exported.serialize(), weight_vals, manifest,
                         path_prefix)


class InferenceArtifact:
    """Deserialized artifact: StableHLO executable + device-placed weights."""

    def __init__(self, exported, weights: List, manifest: dict):
        self.exported = exported
        self.weights = weights  # device arrays, call order
        self.manifest = manifest
        self.feed_names = [f["name"] for f in manifest["feeds"]]
        self.feed_specs = {f["name"]: (tuple(f["shape"]), f["dtype"])
                           for f in manifest["feeds"]}
        self.n_fetches = manifest["n_fetches"]

    @classmethod
    def load(cls, path_prefix: str):
        import jax.numpy as jnp

        from ..jit.artifact_cache import require_export

        with open(path_prefix + ".pdmodel", "rb") as f:
            exported = require_export().deserialize(bytearray(f.read()))
        with open(path_prefix + ".manifest.json") as f:
            manifest = json.load(f)
        with open(path_prefix + ".pdiparams", "rb") as f:
            z = np.load(io.BytesIO(f.read()))
            weights = [jnp.asarray(z[f"w{i}"])
                       for i in range(manifest["n_weights"])]
        return cls(exported, weights, manifest)

    def run(self, feed_vals: Sequence):
        """feed_vals in manifest feed order (device or host arrays)."""
        import jax.numpy as jnp

        args = list(self.weights) + [jnp.asarray(v) for v in feed_vals]
        out = self.exported.call(*args)
        return list(out) if isinstance(out, (tuple, list)) else [out]

    def save(self, path_prefix: str) -> str:
        """Re-serialize this artifact to a new prefix (same triple)."""
        return _write_triple(self.exported.serialize(), self.weights,
                             self.manifest, path_prefix)

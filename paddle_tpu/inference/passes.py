"""Inference analysis passes over imported program IR.

Reference: the inference engine's analysis pass stack
(paddle/fluid/inference/analysis/*, ir_passes: constant folding,
conv+bn fold, identity elimination, dead-code elimination — a slice of the
161 ir passes). TPU framing: XLA performs instruction-level fusion at
compile time, so the passes that matter here are the PROGRAM-level ones
XLA never sees — shrinking the imported op list (smaller traces, faster
compiles) and folding parameter-only math into the weights once instead of
per run. Applied by the Predictor when Config.switch_ir_optim is on.
"""
from __future__ import annotations

from typing import List

import numpy as np

__all__ = ["run_inference_passes", "dead_code_elimination",
           "constant_folding", "identity_elimination", "fold_conv_bn"]


def _used_names(op):
    return [a for args in op.inputs.values() for a in args]


def _fresh_param_name(prog, base):
    """Program-unique name: a per-call counter would collide across the
    multi-round pass pipeline (a later round's fold overwriting an earlier
    round's folded param while its ops still read it)."""
    i = 0
    while f"{base}{i}" in prog.params:
        i += 1
    return f"{base}{i}"


def _out_names(op):
    return [a for args in op.outputs.values() for a in args]


def dead_code_elimination(prog):
    """Drop ops whose outputs reach no fetch (back-to-front liveness)."""
    b0 = prog.blocks[0]
    live = set(prog.fetch_names)
    keep: List = []
    for op in reversed(b0.ops):
        if op.type == "fetch" or any(n in live for n in _out_names(op)):
            keep.append(op)
            live.update(_used_names(op))
    removed = len(b0.ops) - len(keep)
    b0.ops = list(reversed(keep))
    return removed


def identity_elimination(prog):
    """Rewrite no-op ops (inference dropout, scale(1,0), assign) as name
    aliases and drop them. Aliases resolve in program order and are
    invalidated when a kept op redefines the name (imported programs can be
    non-SSA after the reference's inplace/memory passes)."""
    b0 = prog.blocks[0]
    from ..interop.importer import OpDesc, dropout_infer_scale

    alias = {}
    kept = []
    for op in b0.ops:
        # resolve live aliases in this op's inputs first
        for k, args in op.inputs.items():
            op.inputs[k] = [alias.get(a, a) for a in args]
        if op.type == "dropout":
            # 'downgrade_in_infer' (the fluid default) is NOT an identity
            # at inference: out = x * (1 - p). Rewrite it to a scale op
            # (matching the reference's delete_dropout_op_pass); only
            # 'upscale_in_train' / p == 0 alias away.
            s = dropout_infer_scale(op.attrs)
            if s != 1.0:
                sc = OpDesc.__new__(OpDesc)
                sc.type = "scale"
                sc.inputs = {"X": [op.in1("X")]}
                sc.outputs = {"Out": [op.out1("Out")]}
                sc.attrs = {"scale": s, "bias": 0.0,
                            "bias_after_scale": True}
                sc.attr_types = {}
                op = sc
        is_identity = (
            op.type == "dropout"
            or op.type == "assign"
            or (op.type == "scale"
                and op.attrs.get("scale", 1.0) == 1.0
                and op.attrs.get("bias", 0.0) == 0.0)
        )
        if is_identity:
            src = op.in1("X")
            if src is not None:
                for dst in _out_names(op):
                    alias[dst] = src
                continue
        kept.append(op)
        for n in _out_names(op):  # redefinition kills any stale alias
            alias.pop(n, None)
            for dst in [d for d, s in alias.items() if s == n]:
                alias.pop(dst)
    removed = len(b0.ops) - len(kept)
    b0.ops = kept
    # fetch ops were alias-resolved in program order above; fetch_names must
    # track them (programs without fetch ops use the end-of-program aliases)
    new_fetch = [op.in1("X") for op in b0.ops if op.type == "fetch"]
    prog.fetch_names = (new_fetch if new_fetch else
                        [alias.get(n, n) for n in prog.fetch_names])
    return removed


def constant_folding(prog):
    """Pre-compute ops whose every input is a parameter/constant; the
    result becomes a parameter (runs once at load, not per inference)."""
    import jax.numpy as jnp

    from ..interop.importer import _run_op

    b0 = prog.blocks[0]
    const = set(prog.params)
    kept, folded = [], 0
    V = {k: jnp.asarray(v) for k, v in prog.params.items()}
    for op in b0.ops:
        ins = _used_names(op)
        if (op.type not in ("feed", "fetch") and ins
                and all(n in const for n in ins)):
            try:
                _run_op(op, V, jnp)
            except NotImplementedError:
                kept.append(op)
                continue
            for n in _out_names(op):
                if n in V:
                    prog.params[n] = np.asarray(V[n])
                    const.add(n)
            folded += 1
            continue
        kept.append(op)
    b0.ops = kept
    return folded


def fold_conv_bn(prog):
    """conv2d -> batch_norm (inference stats) folds into the conv weights:
    w' = w * s / sqrt(v + eps), plus one bias add — the classic
    conv_bn_fuse_pass."""
    b0 = prog.blocks[0]
    producers = {}
    consumers: dict = {}
    for op in b0.ops:
        for n in _out_names(op):
            producers[n] = op
        for n in _used_names(op):
            consumers.setdefault(n, []).append(op)

    from ..interop.importer import OpDesc

    folded = 0
    kept = []
    for op in b0.ops:
        if op.type != "batch_norm":
            kept.append(op)
            continue
        x = op.in1("X")
        conv = producers.get(x)
        needed = all(op.in1(k) in prog.params
                     for k in ("Scale", "Bias", "Mean", "Variance"))
        if (conv is None or conv.type != "conv2d" or not needed
                or conv.in1("Filter") not in prog.params
                or len(consumers.get(x, [])) != 1):
            kept.append(op)
            continue
        w = prog.params[conv.in1("Filter")]
        s = prog.params[op.in1("Scale")]
        b = prog.params[op.in1("Bias")]
        m = prog.params[op.in1("Mean")]
        v = prog.params[op.in1("Variance")]
        eps = op.attrs.get("epsilon", 1e-5)
        factor = s / np.sqrt(v + eps)
        folded_w = (w * factor.reshape(-1, 1, 1, 1)).astype(w.dtype)
        filt = conv.in1("Filter")
        if len(consumers.get(filt, [])) > 1:
            # weight sharing: folding in place would corrupt the other
            # consumers — write under a fresh name and repoint ONLY this
            # conv (the shared original stays intact)
            fresh = _fresh_param_name(prog, "__folded_w_")
            prog.params[fresh] = folded_w
            conv.inputs["Filter"] = [fresh]
        else:
            prog.params[filt] = folded_w
        bias_name = _fresh_param_name(prog, "__folded_bias_")
        prog.params[bias_name] = (b - m * factor).astype(w.dtype)
        # conv output feeds a bias add that writes the bn's output name
        add = OpDesc.__new__(OpDesc)
        add.type = "elementwise_add"
        add.inputs = {"X": [x], "Y": [bias_name]}
        add.outputs = {"Out": [op.out1("Y")]}
        add.attrs = {"axis": 1}
        add.attr_types = {}  # serializer infers types for pass-made ops
        kept.append(add)
        folded += 1
    b0.ops = kept
    return folded


_DEFAULT_PASSES = (identity_elimination, fold_conv_bn, constant_folding,
                   dead_code_elimination)


def prune_params(prog):
    """Drop parameters no surviving op reads (folded BN stats, folded
    constants' inputs): they would otherwise ship to device on every run
    of the jitted artifact."""
    b0 = prog.blocks[0]
    used = set()
    for op in b0.ops:
        used.update(_used_names(op))
    used.update(prog.fetch_names)
    dead = [n for n in prog.params if n not in used]
    for n in dead:
        del prog.params[n]
    return len(dead)


def run_inference_passes(prog, passes=_DEFAULT_PASSES):
    """Apply the pass pipeline until fixpoint (max 4 rounds) + a final
    param prune; returns a {pass_name: total_rewrites} report."""
    report = {p.__name__: 0 for p in passes}
    for _ in range(4):
        changed = 0
        for p in passes:
            n = p(prog)
            report[p.__name__] += n
            changed += n
        if not changed:
            break
    report["prune_params"] = prune_params(prog)
    return report

"""Commit-ordering rule (F003): the MANIFEST write post-dominates payloads.

PR 2's crash-safety design rests on one ordering invariant: inside a
checkpoint commit, ``MANIFEST.json`` is written LAST — after every payload
entry has been written and fsynced — so a crash at any earlier point
leaves an invisible temp dir, never a manifest describing bytes that are
not on disk. Until now that invariant was enforced by convention and by
the fault-injection torture tests (which sample crash points, they do not
*prove* the ordering). This rule proves it statically:

F003  in a function that writes the manifest (a ``_write_file`` /
      ``atomic_write`` / ``write_file`` call whose arguments reference
      ``MANIFEST_NAME`` or the literal ``"MANIFEST.json"``), every
      payload write (the same write calls NOT referencing the manifest)
      must be **post-dominated** by a manifest write on the normal-flow
      CFG — i.e. every path from the payload write to the function's
      normal exit passes through the manifest write. Exception paths are
      exempt by construction: an aborted commit writes no manifest and
      is invisible, which is the protocol working as designed. The
      finding names the violating path (the payload write that can reach
      exit before/without the manifest).

Scope: the rule triggers only on functions that write the manifest
themselves, so ``save_shard`` (payload-only; rank 0's
``finalize_sharded`` commits later) and generic write helpers stay out of
scope — the cross-rank half of the ordering is the barrier's job, checked
at runtime by the torture tests.

The checker records every (path, function) pair it proved in
``self.proved`` so the suite can assert the live
``robustness/checkpoint.py`` commit functions were actually analyzed
rather than silently skipped.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from . import dataflow
from .callgraph import walk_stop_at_defs
from .engine import Checker, FileContext, Finding, register_rule

F003 = register_rule(
    "F003",
    "checkpoint commit functions write the MANIFEST last: the manifest "
    "write post-dominates every payload write on the normal-flow CFG",
    "a manifest that can land before (or without) a payload write "
    "describes bytes not yet on disk — a crash in the gap commits a "
    "checkpoint that validates against nothing; the PR-2 invariant, "
    "machine-checked instead of convention-checked")

_WRITE_LEAFS = {"_write_file", "atomic_write", "write_file"}
_MANIFEST_MARKERS = {"MANIFEST_NAME", "MANIFEST.json"}
_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _mentions_manifest(call: ast.Call) -> bool:
    for arg in list(call.args) + [k.value for k in call.keywords]:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in _MANIFEST_MARKERS:
                return True
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in _MANIFEST_MARKERS:
                return True
            if isinstance(sub, ast.Constant) and \
                    sub.value in _MANIFEST_MARKERS:
                return True
    return False


class CommitOrderChecker(Checker):
    name = "commit_order"

    def __init__(self):
        self.proved: List[Tuple[str, str]] = []   # (path, function name)

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        # cheap module pre-filter before any CFG work
        if "MANIFEST" not in ctx.source:
            return ()
        df: dataflow.DataflowIndex = shared["dataflow"]
        out: List[Finding] = []
        for node in ctx.walk():
            if isinstance(node, _FN_DEFS):
                out.extend(self._check_function(ctx, df, node))
        return out

    def _check_function(self, ctx, df, fdef) -> Iterable[Finding]:
        manifest_writes: List[ast.Call] = []
        payload_writes: List[ast.Call] = []
        for sub in walk_stop_at_defs(fdef):
            if not isinstance(sub, ast.Call) or _leaf(sub) not in \
                    _WRITE_LEAFS:
                continue
            (manifest_writes if _mentions_manifest(sub)
             else payload_writes).append(sub)
        if not manifest_writes:
            return ()
        cfg = df.cfg(fdef, ctx.path)
        manifest_nodes = {cfg.node_of(c) for c in manifest_writes}
        manifest_nodes.discard(None)
        if not manifest_nodes:
            return ()
        pdom = df.postdom(fdef, ctx.path, kinds=dataflow.FLOW_ONLY)
        out = []
        clean = True
        for call in payload_writes:
            idx = cfg.node_of(call)
            if idx is None:
                continue
            if manifest_nodes & pdom[idx]:
                continue
            clean = False
            path = cfg.find_path(idx, dataflow.CFG.EXIT,
                                 avoid=set(manifest_nodes),
                                 kinds=dataflow.FLOW_ONLY)
            desc = cfg.describe_path(path) if path else \
                "<manifest precedes this write on every path>"
            f = self.finding(
                ctx, F003, call,
                f"{cfg.name}(): payload write is not post-dominated by the "
                f"MANIFEST write — it can reach commit completion on the "
                f"path [{desc}] after the manifest already landed (or "
                f"without one); write every payload entry before the "
                f"manifest")
            if f is not None:
                out.append(f)
        if clean:
            self.proved.append((ctx.path, fdef.name))
        return out

"""Donation-safety rules: buffer donation is a liveness contract, not a hint.

``jax.jit(..., donate_argnums=...)`` lets XLA reuse an input buffer for an
output — the whole reason the fused train step updates params in place in
HBM. It also creates two bug shapes the type system never sees:

D001  a donated binding is DEAD after the call. Reading it afterwards in
      the enclosing scope returns a deleted buffer (jax raises at best,
      returns garbage under some backends at worst). The safe idiom
      rebinds: ``params = step(params, ...)``.

D002  the jitted function's return tuple must order donated-buffer
      outputs BEFORE batch outputs. jax pairs donated inputs with outputs
      of equal abstract shape in tuple order; a batch-sharded model
      output that happens to share a donated param's global shape steals
      the alias slot and fails on the local byte-size mismatch — the
      exact latent ``TrainStep`` bug PR 8 fixed by hand (outputs
      reordered so donated params/slots/residuals pair before the
      batch-sharded out_vals). The checker tracks which return elements
      derive from donated parameters via an intraprocedural taint pass:
      an element whose dataflow never touches a donated parameter is a
      pure data output, and it may not precede one that does.

Both rules only judge sites they can RESOLVE statically (a ``jax.jit``
call with ``donate_argnums`` whose function argument is a def in the same
module scope, or a binding assigned from one); dynamic dispatch is out of
scope by design — no false positives from code the AST cannot see.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .callgraph import dotted_name
from .engine import Checker, FileContext, Finding, register_rule

D001 = register_rule(
    "D001",
    "no read of a donated binding after the donating jit call in the "
    "enclosing scope",
    "donation invalidates the input buffer: a later read returns a deleted "
    "array — rebind the result (params = step(params, ...)) instead")

D002 = register_rule(
    "D002",
    "a donating jitted function returns donated-buffer outputs before "
    "pure batch outputs in its return tuple",
    "jax pairs donated inputs with outputs of equal abstract shape in "
    "tuple order; a batch output sharing a donated param's global shape "
    "steals the alias slot and fails on the local byte-size mismatch — "
    "the PR-8 TrainStep donation-alias bug, now machine-checked")

_JIT_NAMES = {"jit", "pjit"}


def _is_jit(func: ast.AST) -> bool:
    d = dotted_name(func)
    return d is not None and d.rsplit(".", 1)[-1] in _JIT_NAMES


def _donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The static donate_argnums of a jit/pjit call, or None."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
                else:
                    return None
            return tuple(out)
        return None
    return None


def _donating_jit_call(call: ast.Call):
    """(fn_expr, argnums) when ``call`` is jit/pjit(..., donate_argnums=…)."""
    if not (isinstance(call, ast.Call) and _is_jit(call.func)):
        return None
    nums = _donate_argnums(call)
    if nums is None or not call.args:
        return None
    return call.args[0], nums


def _scope_defs(body) -> Dict[str, ast.FunctionDef]:
    """FunctionDefs visible by bare name in one scope body."""
    defs = {}
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[stmt.name] = stmt
    return defs


def _names_loaded(node: ast.AST) -> Set[str]:
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            out.add(sub.id)
    return out


def _names_stored(node: ast.AST) -> Set[str]:
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            out.add(sub.id)
    return out


class DonationSafetyChecker(Checker):
    name = "donation"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        # both rules hinge on a literal donate_argnums= at a jit site —
        # the cheap source test skips the per-scope pass for the ~99% of
        # files that never donate
        if "donate_argnums" not in ctx.source:
            return []
        out: List[Optional[Finding]] = []
        # every scope: module body + each function body
        scopes = [ctx.tree.body]
        for node in ctx.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            out.extend(self._check_scope(ctx, body))
        return [f for f in out if f is not None]

    # -- one lexical scope ----------------------------------------------------
    def _check_scope(self, ctx: FileContext, body) -> List[Optional[Finding]]:
        findings: List[Optional[Finding]] = []
        defs = _scope_defs(body)
        jit_bindings: Dict[str, Tuple[ast.AST, Tuple[int, ...]]] = {}
        # donated-dead bindings: name -> the call statement that killed it
        dead: Dict[str, ast.AST] = {}

        for stmt in body:
            # reads first: a read of a dead binding in this statement is a
            # violation even if the statement also rebinds it afterwards
            # (python evaluates the RHS before the store)
            stores = _names_stored(stmt)
            newly_bound: Set[str] = set()
            # a def/class statement only CAPTURES names — when it runs is
            # unknowable here, so its interior is out of this scope's pass
            is_def = isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                       ast.ClassDef))
            for sub in () if is_def else ast.walk(stmt):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and sub.id in dead:
                    findings.append(self.finding(
                        ctx, D001, sub,
                        f"read of '{sub.id}' after it was donated to a "
                        "jitted call — the buffer is dead; rebind the "
                        "call's result instead"))
                    dead.pop(sub.id, None)   # report once per kill
            # track new donating-jit bindings + donating calls (def/class
            # interiors are their own scopes — handled there)
            for sub in () if is_def else ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                dj = _donating_jit_call(sub)
                if dj is not None:
                    fn_expr, nums = dj
                    # D002 on the wrapped function when resolvable here
                    fn_def = None
                    if isinstance(fn_expr, ast.Name):
                        fn_def = defs.get(fn_expr.id)
                    findings.extend(self._check_return_order(
                        ctx, fn_def, nums))
                    # binding form: step = jax.jit(f, donate_argnums=...)
                    if isinstance(stmt, ast.Assign) and stmt.value is sub:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                jit_bindings[tgt.id] = (fn_expr, nums)
                                newly_bound.add(tgt.id)
                    # direct-call form: jax.jit(f, donate_argnums=...)(a, b)
                    continue
                # call of a known donating binding: args at donated
                # positions become dead after this statement
                callee = sub.func
                nums = None
                if isinstance(callee, ast.Name) and \
                        callee.id in jit_bindings:
                    nums = jit_bindings[callee.id][1]
                elif isinstance(callee, ast.Call):
                    dj = _donating_jit_call(callee)
                    if dj is not None:
                        nums = dj[1]
                if nums is None:
                    continue
                for i in nums:
                    if i < len(sub.args) and \
                            isinstance(sub.args[i], ast.Name):
                        dead[sub.args[i].id] = stmt
            # stores after the reads: rebinding resurrects the name (but a
            # binding created by this very statement survives it)
            for name in stores:
                dead.pop(name, None)
                if name not in newly_bound:
                    jit_bindings.pop(name, None)
        # decorator form of D002: @partial(jax.jit, donate_argnums=...)
        for stmt in body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in stmt.decorator_list:
                if isinstance(dec, ast.Call):
                    nums = None
                    d = dotted_name(dec.func)
                    leaf = d.rsplit(".", 1)[-1] if d else None
                    if leaf in _JIT_NAMES:
                        nums = _donate_argnums(dec)
                    elif leaf == "partial" and dec.args and \
                            _is_jit(dec.args[0]):
                        nums = _donate_argnums(dec)
                    if nums:
                        findings.extend(self._check_return_order(
                            ctx, stmt, nums))
        return findings

    # -- D002: taint the return tuple ----------------------------------------
    def _check_return_order(self, ctx: FileContext,
                            fn_def, nums: Sequence[int]
                            ) -> List[Optional[Finding]]:
        if fn_def is None or not nums:
            return []
        params = [a.arg for a in fn_def.args.args]
        donated = {params[i] for i in nums if i < len(params)}
        if not donated:
            return []
        taint = self._taint(fn_def, params)
        out: List[Optional[Finding]] = []
        for node in ast.walk(fn_def):
            if not (isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Tuple)):
                continue
            # classification per element: donated-derived / pure-data
            first_pure: Optional[int] = None
            for i, elt in enumerate(node.value.elts):
                src: Set[str] = set()
                for name in _names_loaded(elt):
                    src |= taint.get(name, set())
                if not src:
                    continue                      # constants: neutral
                if src & donated:
                    if first_pure is not None:
                        out.append(self.finding(
                            ctx, D002, node,
                            f"donated-buffer output (element {i}, derived "
                            f"from {'/'.join(sorted(src & donated))}) is "
                            "ordered after a pure batch output in "
                            f"{fn_def.name}()'s return tuple — the batch "
                            "output can steal the donation alias slot"))
                        break
                elif first_pure is None:
                    first_pure = i
        return out

    @staticmethod
    def _taint(fn_def, params: List[str]) -> Dict[str, Set[str]]:
        """name -> set of parameter names its dataflow touches. One
        forward pass in statement order, joining over assignments; calls
        taint their results with every argument's taint (conservative)."""
        taint: Dict[str, Set[str]] = {p: {p} for p in params}

        def expr_taint(e) -> Set[str]:
            src: Set[str] = set()
            for name in _names_loaded(e):
                src |= taint.get(name, set())
            return src

        def visit(body):
            for stmt in body:
                if isinstance(stmt, ast.Assign):
                    src = expr_taint(stmt.value)
                    for tgt in stmt.targets:
                        for name in _names_stored(tgt):
                            taint[name] = taint.get(name, set()) | src
                elif isinstance(stmt, ast.AugAssign):
                    src = expr_taint(stmt.value)
                    for name in _names_stored(stmt.target):
                        taint[name] = taint.get(name, set()) | src
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    src = expr_taint(stmt.value)
                    for name in _names_stored(stmt.target):
                        taint[name] = taint.get(name, set()) | src
                elif isinstance(stmt, (ast.For,)):
                    src = expr_taint(stmt.iter)
                    for name in _names_stored(stmt.target):
                        taint[name] = taint.get(name, set()) | src
                    visit(stmt.body)
                    visit(stmt.orelse)
                elif isinstance(stmt, (ast.If, ast.While)):
                    visit(stmt.body)
                    visit(stmt.orelse)
                elif isinstance(stmt, ast.With):
                    visit(stmt.body)
                elif isinstance(stmt, ast.Try):
                    visit(stmt.body)
                    for h in stmt.handlers:
                        visit(h.body)
                    visit(stmt.orelse)
                    visit(stmt.finalbody)

        # two passes so later-defined helpers feeding earlier names settle
        visit(fn_def.body)
        visit(fn_def.body)
        return taint

"""Trace-purity rules: no host-side effects inside traced functions.

Anything jax traces (jit/pjit/shard_map/scan/grad bodies) runs its Python
once at trace time; a ``time.time()`` or ``random.random()`` inside bakes
one stale value into the compiled program forever, and a ``.item()`` /
``device_get`` forces a host sync that silently serializes the pipeline.
The reference framework hits the same class of bug with CINN/composite
ops capturing host state; here the trace cache (framework/autograd) makes
it worse — the baked value also becomes the cached value.

T001  functions on the trace path must not call wall-clock, host RNG, or
      host-sync primitives. The trace path is detected structurally:
      decorated with / passed to jit, pjit, to_static, shard_map,
      compat_shard_map, vmap, pmap, grad, value_and_grad, checkpoint,
      remat, scan, fori_loop, while_loop, cond, switch, or custom_vjp.

T002  the grad_comm wire-codec functions (encode/decode/scale/residual/
      absmax transforms in distributed/grad_comm.py) must be pure jnp —
      no numpy, no host sync. ISSUE 8 shares them VERBATIM between the
      eager sync and the compiled train step (sync_async /
      TrainStep(grad_comm=)); one `np.` call would run fine eagerly and
      silently constant-fold (or crash) inside the trace, forking the two
      paths the whole design promises are identical.

T003  (ISSUE 11) T001 through the project call graph: an impure call
      reached from a traced function via ANY chain of confidently-
      resolved calls is flagged at the traced fn's call site, with the
      chain in the message. Functions that branch on ``_in_trace()``
      (the collective layer's dual-path contract) and the dual-path
      modules themselves (collective.py, distributed_ft.py, autograd.py)
      are trusted boundaries — their host halves never run in-trace.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Checker, FileContext, Finding, register_rule

T001 = register_rule(
    "T001",
    "no wall-clock / host-RNG / host-sync calls inside traced functions",
    "traced Python runs once: the host value is frozen into the compiled "
    "program (and the trace cache), and .item()-style syncs stall the "
    "device pipeline")

T002 = register_rule(
    "T002",
    "grad_comm wire-codec functions are pure jnp (no numpy, no host sync)",
    "the codec transforms are shared verbatim by the eager sync and the "
    "compiled train step; numpy or a host sync inside one would silently "
    "fork the eager and traced wire formats (or bake a stale host value "
    "into the trace cache)")

T003 = register_rule(
    "T003",
    "no wall-clock / host-RNG / host-sync reached through ANY call chain "
    "from a traced function (project call graph, confident edges)",
    "T001 sees only the traced body; a helper two calls away with a "
    "time.time() bakes the same stale host value into the compiled "
    "program — the blind spot where the real bugs live. Dual-path "
    "functions that branch on _in_trace() (the collective layer's "
    "contract) are trusted boundaries and not traversed")

# modules whose public functions legally carry host-side halves: the
# collective layer and the fault-tolerance runtime both branch on
# _in_trace()/trace-state internally; traversing into them would flag
# every traced fn that issues a guarded collective
_T003_BOUNDARY_SUFFIXES = (
    "distributed/collective.py",
    "robustness/distributed_ft.py",
    "framework/autograd.py",
)
_T003_MAX_DEPTH = 10

# the codec module, and the function-name parts that mark a wire-codec
# transform in it (module-level defs only)
_CODEC_FILE_SUFFIX = "distributed/grad_comm.py"
_CODEC_NAME_PARTS = ("encode", "decode", "scale", "residual", "absmax",
                     "blocks")

# call targets that put a function on the trace path
_TRACERS = {
    "jit", "pjit", "to_static", "shard_map", "compat_shard_map", "vmap",
    "pmap", "grad", "value_and_grad", "checkpoint", "remat", "scan",
    "fori_loop", "while_loop", "cond", "switch", "custom_vjp", "custom_jvp",
}

# dotted-name suffixes that are impure on the trace path
_IMPURE_DOTTED = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.sleep", "datetime.now", "datetime.utcnow", "os.urandom",
    "jax.device_get",
}
_IMPURE_MODULES = {"random", "np.random", "numpy.random"}
_IMPURE_METHODS = {"item", "block_until_ready"}
# jax.random is keyed FUNCTIONAL rng — same key, same bits, at trace
# time or run time — the sanctioned way to sample inside a trace
# (serving/sampler.py derives per-request keys in-program). Only the
# module-head match below needs the carve-out; jax.random has no
# wall-clock/sync members.
_PURE_RNG_HEADS = ("jax.random",)


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _leaf(d: str) -> str:
    return d.rsplit(".", 1)[-1]


class TracePurityChecker(Checker):
    name = "trace_purity"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        # a file with no tracer token anywhere has no traced functions;
        # the substring test is ~100x cheaper than the scope scan
        if not any(t in ctx.source for t in _TRACERS):
            return [f for f in self._check_codec_purity(ctx)
                    if f is not None]
        traced = self._traced_functions(ctx.tree)
        out: List[Optional[Finding]] = []
        for fn in traced:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                why = self._impurity(node)
                if why:
                    fname = getattr(fn, "name", "<lambda>")
                    out.append(self.finding(
                        ctx, T001, node,
                        f"{why} inside traced function {fname}()"))
        out.extend(self._check_codec_purity(ctx))
        index = shared.get("project_index")
        if index is not None:
            for fn in traced:
                out.extend(self._check_transitive(ctx, fn, index))
        return [f for f in out if f is not None]

    # -- T003: impurity through the call graph -------------------------------
    def _check_transitive(self, ctx: FileContext, fn, index):
        node = index.node_for(fn)
        if node is None:
            return
        fname = getattr(fn, "name", "<lambda>")
        # own subtree (incl. closures) is T001's job: exclude it here
        own = {node.qualname}
        frontier = list(node.children)
        while frontier:
            q = frontier.pop()
            if q in own:
                continue
            own.add(q)
            sub = index.functions.get(q)
            if sub is not None:
                frontier.extend(sub.children)
        for dotted, call in node.calls:
            for callee_q in index.resolve(dotted, node, fallback=False):
                if callee_q in own:
                    continue
                chain = self._impure_chain(index, callee_q, own)
                if chain is not None:
                    why, names = chain
                    yield self.finding(
                        ctx, T003, call,
                        f"traced function {fname}() reaches {why} through "
                        f"{' -> '.join(names)}")
                    break   # one report per call site

    @classmethod
    def _impure_chain(cls, index, qualname, exclude,
                      _depth=0, _seen=None):
        """(why, [names...]) for the first impure call reachable from
        ``qualname`` over confident edges, honoring trusted boundaries."""
        if _depth > _T003_MAX_DEPTH:
            return None
        fn = index.functions.get(qualname)
        if fn is None or cls._is_boundary(fn):
            return None
        memo = index.__dict__.setdefault("_t003_memo", {})
        if qualname in memo:
            hit = memo[qualname]
            return None if hit is None else (hit[0], [fn.name] + hit[1])
        seen = _seen if _seen is not None else set(exclude)
        if qualname in seen:
            return None
        seen.add(qualname)
        for dotted, call in fn.calls:
            why = cls._impurity(call)
            if why:
                memo[qualname] = (why, [])
                return why, [fn.name]
        for callee_q in index.callees(qualname, fallback=False):
            sub = cls._impure_chain(index, callee_q, exclude,
                                    _depth + 1, seen)
            if sub is not None:
                why, names = sub
                memo[qualname] = (why, names)
                return why, [fn.name] + names
        memo[qualname] = None
        return None

    @staticmethod
    def _is_boundary(fn) -> bool:
        if fn.has_in_trace_guard:
            return True
        path = fn.path.replace("\\", "/")
        return any(path.endswith(sfx) for sfx in _T003_BOUNDARY_SUFFIXES)

    # -- T002: grad_comm codec purity ---------------------------------------
    def _check_codec_purity(self, ctx: FileContext):
        path = ctx.path.replace("\\", "/")
        if not path.endswith(_CODEC_FILE_SUFFIX):
            return []
        out = []
        for fn in ctx.tree.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = fn.name.lstrip("_")
            if not any(part in name for part in _CODEC_NAME_PARTS):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in ("np", "numpy"):
                    out.append(self.finding(
                        ctx, T002, node,
                        f"numpy use in wire-codec function {fn.name}()"))
                elif isinstance(node, ast.Call):
                    why = self._impurity(node)
                    if why:
                        out.append(self.finding(
                            ctx, T002, node,
                            f"{why} in wire-codec function {fn.name}()"))
        return out

    # -- trace-path detection ----------------------------------------------
    def _traced_functions(self, tree: ast.Module):
        """FunctionDefs/Lambdas that are (a) decorated by a tracer, or
        (b) passed by name or inline to a tracer call in the same scope."""
        traced = []
        seen: Set[int] = set()

        def mark(fn):
            if id(fn) not in seen:
                seen.add(id(fn))
                traced.append(fn)

        # (a) decorator form, incl. functools.partial(jax.jit, ...)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._is_tracer_expr(dec):
                        mark(node)

        # (b) call-argument form: tracer(fn_name_or_lambda, ...)
        # resolve Name args against FunctionDefs in every enclosing scope
        self._scan_scope(tree, {}, mark)
        return traced

    def _scan_scope(self, scope_node, visible, mark):
        local = dict(visible)
        body = getattr(scope_node, "body", [])
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local[stmt.name] = stmt
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        self._is_tracer_expr(node.func):
                    for a in list(node.args) + \
                            [k.value for k in node.keywords]:
                        if isinstance(a, ast.Lambda):
                            mark(a)
                        elif isinstance(a, ast.Name) and a.id in local:
                            mark(local[a.id])
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_scope(stmt, local, mark)
            elif isinstance(stmt, (ast.ClassDef, ast.If, ast.Try, ast.With,
                                   ast.For, ast.While)):
                self._scan_scope(stmt, local, mark)

    @staticmethod
    def _is_tracer_expr(node: ast.AST) -> bool:
        d = _dotted(node)
        if d is not None and _leaf(d) in _TRACERS:
            return True
        # partial(jax.jit, ...) / jax.jit(static_argnums=...) decorator call
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None:
                leaf = _leaf(d)
                if leaf in _TRACERS:
                    return True
                if leaf == "partial" and node.args:
                    d0 = _dotted(node.args[0])
                    if d0 is not None and _leaf(d0) in _TRACERS:
                        return True
        return False

    # -- impurity detection --------------------------------------------------
    @staticmethod
    def _impurity(call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if d is None:
            return None
        for suffix in _IMPURE_DOTTED:
            if d == suffix or d.endswith("." + suffix):
                return f"host call {suffix}()"
        head = d.rsplit(".", 1)[0] if "." in d else ""
        if any(head == p or head.endswith("." + p)
               for p in _PURE_RNG_HEADS):
            return None
        if head in _IMPURE_MODULES or any(
                head == m or head.endswith("." + m) for m in _IMPURE_MODULES):
            return f"host RNG {d}()"
        if "." in d and _leaf(d) in _IMPURE_METHODS:
            return f"host sync .{_leaf(d)}()"
        return None

"""Registry-drift rules: flags and metric schemas stay declared.

Two registries anchor framework-wide conventions: ``framework/flags.py``
(_FLAGS — every FLAGS_* knob with its default and type) and
``observability/metrics.py`` (every metric family declared once with a
fixed label set). Both drift silently: a ``flag("FLAGS_typo")`` read
returns the fallback forever, and a family bound with a different label
set raises only on the first hot-path increment in production. PR 7's
trigger was real: FLAGS_selected_tpus was read by distributed/env.py and
set by launch/main.py but declared nowhere.

R001  every FLAGS_* name referenced in paddle_tpu/ is declared in the
      framework/flags.py _FLAGS table.
R002  a metric family is declared with one label set everywhere, and
      every resolvable .labels(...)/.bind(...) call passes exactly that
      label set.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .engine import Checker, FileContext, Finding, register_rule

R001 = register_rule(
    "R001",
    "every FLAGS_* read/write names a flag declared in framework/flags.py",
    "an undeclared flag read silently returns the call-site fallback "
    "forever; declaring it gives env-override, typing, and one visible "
    "default")
R002 = register_rule(
    "R002",
    "metric families keep one label schema across declaration and binding",
    "label-set mismatches raise at first bind — usually on a hot path in "
    "production rather than in tests")

_FLAG_RE = re.compile(r"^FLAGS_[A-Za-z0-9_]+$")
_METRIC_CTORS = {"counter", "gauge", "histogram"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _metric_decl(call: ast.Call) -> Optional[Tuple[str, str, Tuple[str, ...]]]:
    """(family_name, kind, label_names) if `call` is reg.counter('x', ...)
    with a literal name; None otherwise. Unresolvable labels= return None
    (we only check what we can prove)."""
    if not isinstance(call.func, ast.Attribute):
        return None
    kind = call.func.attr
    if kind not in _METRIC_CTORS:
        return None
    if not (call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)):
        return None
    name = call.args[0].value
    labels: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "labels":
            if isinstance(kw.value, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in kw.value.elts):
                labels = tuple(e.value for e in kw.value.elts)
            else:
                return None
    return name, kind, labels


class RegistryDriftChecker(Checker):
    name = "registry_drift"

    FLAGS_MODULE = "framework/flags.py"

    # -- pass 1: collect declared flags + metric schemas ---------------------
    def collect(self, ctx: FileContext, shared: dict) -> None:
        if ctx.path.endswith(self.FLAGS_MODULE):
            declared = shared.setdefault("declared_flags", set())
            for node in ctx.walk():
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    value = node.value
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    if (isinstance(value, ast.Dict) and any(
                            isinstance(t, ast.Name) and t.id == "_FLAGS"
                            for t in targets)):
                        for k in value.keys:
                            if isinstance(k, ast.Constant) and \
                                    isinstance(k.value, str):
                                declared.add(k.value)
        schemas = shared.setdefault("metric_schemas", {})
        for node in ctx.walk():
            if isinstance(node, ast.Call):
                decl = _metric_decl(node)
                if decl is None:
                    continue
                name, kind, labels = decl
                schemas.setdefault(name, []).append(
                    (ctx.path, node.lineno, kind, labels))

    # -- pass 2 ---------------------------------------------------------------
    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        out: List[Optional[Finding]] = []
        out.extend(self._check_flags(ctx, shared))
        out.extend(self._check_metric_decl_conflicts(ctx, shared))
        out.extend(self._check_bind_sites(ctx, shared))
        return [f for f in out if f is not None]

    def _check_flags(self, ctx: FileContext, shared: dict):
        if ctx.path.endswith(self.FLAGS_MODULE):
            return
        declared = shared.get("declared_flags", set())
        for node in ctx.walk():
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _FLAG_RE.match(node.value)
                    and node.value not in declared):
                yield self.finding(
                    ctx, R001, node,
                    f"{node.value} is not declared in framework/flags.py "
                    "_FLAGS — reads fall back silently, env overrides are "
                    "ignored")

    def _check_metric_decl_conflicts(self, ctx: FileContext, shared: dict):
        """Report at each declaration that disagrees with the family's
        first-seen schema (first by path,line across the run)."""
        schemas: Dict[str, list] = shared.get("metric_schemas", {})
        for name, decls in schemas.items():
            ordered = sorted(decls)
            _, _, kind0, labels0 = ordered[0]
            for path, line, kind, labels in ordered[1:]:
                if path != ctx.path:
                    continue
                if kind != kind0 or set(labels) != set(labels0):
                    yield Finding(
                        R002, ctx.path, line,
                        f"metric '{name}' redeclared as {kind}{labels} — "
                        f"first declared as {kind0}{labels0}") \
                        if not ctx.waived(R002, line) else None

    def _check_bind_sites(self, ctx: FileContext, shared: dict):
        """Within one file, resolve `var = reg.counter('x', labels=...)`
        then check `var.labels(...)` / `var.bind(...)` kwarg sets."""
        schemas: Dict[str, list] = shared.get("metric_schemas", {})
        var_to_family: Dict[str, str] = {}
        for node in ctx.walk():
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                decl = _metric_decl(node.value)
                if decl is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            var_to_family[t.id] = decl[0]
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("labels", "bind")):
                continue
            family = None
            base = node.func.value
            if isinstance(base, ast.Name) and base.id in var_to_family:
                family = var_to_family[base.id]
            elif isinstance(base, ast.Call):
                decl = _metric_decl(base)
                if decl is not None:
                    family = decl[0]
            if family is None or family not in schemas:
                continue
            if any(k.arg is None for k in node.keywords):
                continue  # **splat: not statically resolvable
            passed = {k.arg for k in node.keywords}
            declared = set(sorted(schemas[family])[0][3])
            if passed != declared:
                yield self.finding(
                    ctx, R002, node,
                    f"metric '{family}' bound with labels "
                    f"{tuple(sorted(passed))} but declared with "
                    f"{tuple(sorted(declared))}")

"""Mesh-axis validity rule (X005): collective axis names must exist.

A ``psum``/``all_gather``/``ppermute`` over axis ``"modle"`` (or over an
axis the mesh was never built with) is a phantom-axis bug: inside a
``shard_map`` region jax raises a NameError-like failure at trace time in
the best case, and in the worst (a spec that ``sanitize_spec`` silently
drops, a constrain over a dead axis) the program runs UNSHARDED with no
error at all. The upcoming pipeline/pallas work multiplies axis-string
plumbing, so the check lands first:

X005  every axis name that *resolvably* reaches a collective site
      (``lax.psum/pmax/pmin/pmean/psum_scatter/all_gather/all_to_all/
      ppermute/axis_index``, the sanctioned ``in_trace_psum``/
      ``in_trace_pmax``), a ``constrain``/``_constrain`` spec, or a
      ``shard_map``/``compat_shard_map`` in/out spec must exist in the
      project's mesh-axis registry. The registry is every axis the
      project can actually construct: the canonical axis constants of the
      mesh module (the module defining ``build_mesh``) plus every axis
      string named at a mesh-construction site (``build_mesh({...})``
      topology keys, ``Mesh(devices, (...))`` name tuples).

Resolution is flow-sensitive and interprocedural-one-hop, composing the
PR-12 dataflow layer with the PR-11 call graph:

- a string literal resolves to itself; tuples/lists resolve element-wise;
- a local name resolves through **reaching definitions** at the use site
  (every reaching assignment's value is resolved recursively);
- a parameter resolves through its default plus the arguments at every
  CONFIDENT call-graph call site (bounded hops);
- a free variable resolves through the lexical chain (enclosing function
  assignments/parameters, then module constants, then the import table —
  ``mesh_mod.AXIS_MODEL`` follows the alias to the mesh module's
  constant).

Anything else (subscripts, call results, conditional expressions,
``*args``) is UNKNOWN and the site is skipped — the rule flags only axis
strings it positively resolved, so it is zero-false-positive by
construction; ``self.stats`` counts sites seen / axes validated so the
suite can assert real coverage rather than vacuous silence.
"""
from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from . import dataflow
from .callgraph import dotted_name, module_of, walk_stop_at_defs
from .engine import Checker, FileContext, Finding, register_rule

X005 = register_rule(
    "X005",
    "axis names reaching collective/constrain/shard_map sites exist in "
    "the mesh-axis registry (canonical mesh-module constants + "
    "build_mesh/Mesh construction sites)",
    "a phantom axis fails at trace time inside shard_map and silently "
    "un-shards under sanitize_spec/constrain outside it — the bug class "
    "the pipeline/pallas axis plumbing will multiply")

_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_MAX_DEPTH = 4

# call leaf -> positional index of the axis argument (lax collectives
# require a lax-rooted dotted name; the sanctioned in_trace_* helpers any)
_LAX_AXIS_ARG = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "axis_index": 0,
}
_SANCTIONED_AXIS_ARG = {"in_trace_psum": 1, "in_trace_pmax": 1}
_CONSTRAIN_LEAFS = {"constrain", "_constrain"}
_SHARD_MAP_LEAFS = {"shard_map", "compat_shard_map"}
_SPEC_CTORS = {"P", "PartitionSpec"}


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _const_strings(expr) -> Optional[FrozenSet[str]]:
    """frozenset of strings for a literal str/tuple-of-str/list-of-str
    expression, else None."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return frozenset((expr.value,))
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in expr.elts:
            s = _const_strings(e)
            if s is None:
                return None
            out |= s
        return frozenset(out)
    return None


class _Env:
    """Resolution context: which file/function an expression lives in."""

    __slots__ = ("ctx", "fdef", "site")

    def __init__(self, ctx: FileContext, fdef, site: Optional[int]):
        self.ctx = ctx
        self.fdef = fdef          # enclosing def (None = module level)
        self.site = site          # CFG node idx of the use (reaching defs)


class MeshAxisChecker(Checker):
    name = "mesh_axes"

    def __init__(self):
        self.stats = {"sites": 0, "axes_validated": 0, "sites_skipped": 0}

    # ---------------------------------------------------------------- pass 1
    def collect(self, ctx: FileContext, shared: dict) -> None:
        st = shared.setdefault("mesh_axes", {
            "registry": set(), "consts": {}, "ctxs": {}, "rev": None,
        })
        st["ctxs"][ctx.path] = ctx
        module = module_of(ctx.path)
        consts: Dict[str, FrozenSet[str]] = {}
        defines_build_mesh = False
        for stmt in ctx.tree.body:
            if isinstance(stmt, _FN_DEFS) and stmt.name == "build_mesh":
                defines_build_mesh = True
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                vals = _const_strings(stmt.value)
                if vals is not None:
                    consts[stmt.targets[0].id] = vals
        st["consts"][module] = consts
        if defines_build_mesh:
            # canonical axes: the mesh module's ALL-CAPS string constants
            for name, vals in consts.items():
                if name.isupper():
                    st["registry"] |= vals
        # mesh-construction sites anywhere: build_mesh({...}) topology
        # keys and Mesh(devices, (names,)) tuples
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            leaf = _leaf(node)
            if leaf == "build_mesh" and node.args and \
                    isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        st["registry"].add(k.value)
            elif leaf == "Mesh" and len(node.args) >= 2:
                vals = _const_strings(node.args[1])
                if vals is not None:
                    st["registry"] |= vals

    # ---------------------------------------------------------------- pass 2
    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        # quick textual pre-filter before any CFG/resolution work
        src = ctx.source
        if not any(k in src for k in ("lax.", "in_trace_p", "constrain",
                                      "shard_map")):
            return ()
        self._shared = shared
        self._df: dataflow.DataflowIndex = shared["dataflow"]
        self._index = shared["project_index"]
        st = shared["mesh_axes"]
        registry = st["registry"]
        out: List[Finding] = []
        for fdef, call in self._sites(ctx):
            axes = self._site_axes(ctx, fdef, call)
            self.stats["sites"] += 1
            if axes is None or not axes:
                self.stats["sites_skipped"] += 1
                continue
            self.stats["axes_validated"] += len(axes)
            unknown = sorted(a for a in axes if a not in registry)
            if unknown:
                f = self.finding(
                    ctx, X005, call,
                    f"{_leaf(call)}: axis name(s) "
                    f"{', '.join(repr(a) for a in unknown)} do not exist "
                    f"in any reachable mesh definition (canonical axis "
                    f"constants or build_mesh/Mesh construction sites) — "
                    f"a phantom axis traces to an error or silently "
                    f"un-shards")
                if f is not None:
                    out.append(f)
        return out

    def _sites(self, ctx) -> Iterable[Tuple[Optional[ast.AST], ast.Call]]:
        """(enclosing def or None, call) for every axis-bearing site."""
        def calls_in(root, fdef):
            for sub in walk_stop_at_defs(root):
                if isinstance(sub, ast.Call) and self._is_site(sub):
                    yield (fdef, sub)

        # module level (outside any def)
        for stmt in ctx.tree.body:
            if not isinstance(stmt, _FN_DEFS):
                yield from calls_in(stmt, None)
        for node in ctx.walk():
            if isinstance(node, _FN_DEFS):
                yield from calls_in(node, node)

    def _is_site(self, call: ast.Call) -> bool:
        leaf = _leaf(call)
        if leaf in _SANCTIONED_AXIS_ARG or leaf in _CONSTRAIN_LEAFS or \
                leaf in _SHARD_MAP_LEAFS:
            return True
        if leaf in _LAX_AXIS_ARG:
            d = dotted_name(call.func)
            return d is not None and "lax" in d.split(".")[:-1]
        if leaf == "partial" and call.args:
            d = dotted_name(call.args[0])
            return d is not None and \
                d.rsplit(".", 1)[-1] in _SHARD_MAP_LEAFS
        return False

    # ------------------------------------------------------------ extraction
    def _site_axes(self, ctx, fdef, call) -> Optional[Set[str]]:
        """All positively-resolved axis strings reaching this site."""
        env = self._env_for(ctx, fdef, call)
        leaf = _leaf(call)
        axes: Set[str] = set()
        if leaf in _LAX_AXIS_ARG or leaf in _SANCTIONED_AXIS_ARG:
            pos = (_LAX_AXIS_ARG.get(leaf)
                   if leaf in _LAX_AXIS_ARG else _SANCTIONED_AXIS_ARG[leaf])
            expr = None
            if len(call.args) > pos and not any(
                    isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
                expr = call.args[pos]
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis"):
                    expr = kw.value
            if expr is not None:
                axes |= self._resolve_axes(expr, env, 0)
        elif leaf in _CONSTRAIN_LEAFS:
            for a in call.args[1:]:
                if isinstance(a, ast.Starred):
                    continue
                axes |= self._resolve_axes(a, env, 0)
        elif leaf in _SHARD_MAP_LEAFS or leaf == "partial":
            specs = []
            if leaf in _SHARD_MAP_LEAFS:
                if len(call.args) > 2:
                    specs.append(call.args[2])
                if len(call.args) > 3:
                    specs.append(call.args[3])
            for kw in call.keywords:
                if kw.arg in ("in_specs", "out_specs"):
                    specs.append(kw.value)
            for s in specs:
                axes |= self._resolve_spec(s, env, 0)
        return axes

    def _env_for(self, ctx, fdef, use_node) -> _Env:
        site = None
        if fdef is not None:
            site = self._df.cfg(fdef, ctx.path).node_of(use_node)
        return _Env(ctx, fdef, site)

    # ------------------------------------------------------------ resolution
    def _resolve_spec(self, expr, env: _Env, depth: int) -> Set[str]:
        """Axis strings inside a PartitionSpec-shaped expression."""
        if depth > _MAX_DEPTH:
            return set()
        if isinstance(expr, ast.Call) and _leaf(expr) in _SPEC_CTORS:
            out: Set[str] = set()
            for a in expr.args:
                if not isinstance(a, ast.Starred):
                    out |= self._resolve_axes(a, env, depth)
            return out
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = set()
            for e in expr.elts:
                if not isinstance(e, ast.Starred):
                    out |= self._resolve_spec(e, env, depth)
            return out
        if isinstance(expr, ast.Name):
            out = set()
            for value, venv in self._name_values(expr.id, env, depth):
                out |= self._resolve_spec(value, venv, depth + 1)
            return out
        return set()

    def _resolve_axes(self, expr, env: _Env, depth: int) -> Set[str]:
        """Axis strings an axis-argument expression positively resolves
        to; unresolvable shapes contribute nothing."""
        if depth > _MAX_DEPTH:
            return set()
        if isinstance(expr, ast.Constant):
            return {expr.value} if isinstance(expr.value, str) else set()
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for e in expr.elts:
                if not isinstance(e, ast.Starred):
                    out |= self._resolve_axes(e, env, depth)
            return out
        if isinstance(expr, ast.Name):
            out = set()
            for value, venv in self._name_values(expr.id, env, depth):
                if isinstance(value, _Param):
                    out |= self._resolve_param(value, depth + 1)
                else:
                    out |= self._resolve_axes(value, venv, depth + 1)
            return out
        if isinstance(expr, ast.Attribute):
            return self._resolve_attr(expr, env)
        return set()

    def _name_values(self, name: str, env: _Env, depth: int):
        """Value expressions (with their env) a name may hold at the use
        site: reaching definitions first, then the lexical chain."""
        if depth > _MAX_DEPTH:
            return []
        results = []
        if env.fdef is not None and env.site is not None:
            rd = self._df.reaching(env.fdef, env.ctx.path)
            cfg = self._df.cfg(env.fdef, env.ctx.path)
            defs = rd.defs_at(env.site, name)
            if defs:
                for didx in defs:
                    if didx == dataflow.CFG.ENTRY:
                        results.append((_Param(env.ctx, env.fdef, name),
                                        env))
                        continue
                    stmt = cfg.nodes[didx].stmt
                    value = self._assign_value(stmt, name)
                    if value is not None:
                        results.append(
                            (value, _Env(env.ctx, env.fdef, didx)))
                return results
        # free variable: enclosing functions, then module scope
        return self._lexical_values(name, env)

    @staticmethod
    def _assign_value(stmt, name: str):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                stmt.targets[0].id == name:
            return stmt.value
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                stmt.target.id == name and stmt.value is not None:
            return stmt.value
        return None

    def _lexical_values(self, name: str, env: _Env):
        """Enclosing-function assignments/params, then module constants
        and the import table."""
        fn_node = None
        if env.fdef is not None:
            fn_node = self._index.node_for(env.fdef)
        while fn_node is not None:
            parent_qual = fn_node.qual.rsplit(".", 1)[0] \
                if "." in fn_node.qual else None
            fn_node = self._index.functions.get(
                f"{fn_node.path}::{parent_qual}") if parent_qual else None
            if fn_node is None:
                break
            fdef = fn_node.node
            assigns = [self._assign_value(s, name)
                       for s in walk_stop_at_defs(fdef)
                       if isinstance(s, (ast.Assign, ast.AnnAssign))]
            assigns = [a for a in assigns if a is not None]
            if assigns:
                penv = _Env(env.ctx, fdef, None)
                return [(a, penv) for a in assigns]
            if name in self._param_names(fdef):
                return [(_Param(env.ctx, fdef, name), env)]
        return self._module_values(name, env.ctx)

    def _module_values(self, name: str, ctx):
        st = self._shared["mesh_axes"]
        module = module_of(ctx.path)
        vals = st["consts"].get(module, {}).get(name)
        if vals is not None:
            return [(ast.Constant(value=v), _Env(ctx, None, None))
                    for v in vals]
        target = self._index.imports.get(module, {}).get(name)
        if target and "." in target:
            mod, leafname = target.rsplit(".", 1)
            vals = st["consts"].get(mod, {}).get(leafname)
            if vals is not None:
                return [(ast.Constant(value=v), _Env(ctx, None, None))
                        for v in vals]
        return []

    def _resolve_attr(self, expr: ast.Attribute, env: _Env) -> Set[str]:
        """``mesh_mod.AXIS_MODEL``-style module-constant references."""
        d = dotted_name(expr)
        if d is None or "." not in d:
            return set()
        head, leafname = d.rsplit(".", 1)
        st = self._shared["mesh_axes"]
        module = module_of(env.ctx.path)
        target = self._index.imports.get(module, {}).get(head, head)
        vals = st["consts"].get(target, {}).get(leafname)
        return set(vals) if vals is not None else set()

    # ------------------------------------------------------- parameter hops
    def _param_names(self, fdef) -> List[str]:
        a = fdef.args
        return [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)]

    def _reverse_calls(self):
        st = self._shared["mesh_axes"]
        if st["rev"] is None:
            rev: Dict[str, List] = {}
            for fn in self._index.functions.values():
                for dotted, call in fn.calls:
                    for q in self._index.resolve(dotted, fn,
                                                 fallback=False):
                        rev.setdefault(q, []).append((fn, call))
            st["rev"] = rev
        return st["rev"]

    def _resolve_param(self, param: "_Param", depth: int) -> Set[str]:
        """Default value plus the argument at every confident call site —
        one interprocedural hop per recursion level, bounded."""
        if depth > _MAX_DEPTH:
            return set()
        fdef = param.fdef
        names = self._param_names(fdef)
        try:
            pos = names.index(param.name)
        except ValueError:
            return set()
        out: Set[str] = set()
        default = self._param_default(fdef, param.name)
        fn_node = self._index.node_for(fdef)
        callers = (self._reverse_calls().get(fn_node.qualname, [])
                   if fn_node is not None else [])
        for caller_fn, call in callers:
            if any(isinstance(a, ast.Starred) for a in call.args) or \
                    any(k.arg is None for k in call.keywords):
                continue
            arg = None
            offset = 1 if (names and names[0] in ("self", "cls")
                           and isinstance(call.func, ast.Attribute)) else 0
            idx = pos - offset
            if 0 <= idx < len(call.args):
                arg = call.args[idx]
            for kw in call.keywords:
                if kw.arg == param.name:
                    arg = kw.value
            if arg is None:
                continue       # omitted at this site -> default covers it
            cctx = self._shared["mesh_axes"]["ctxs"].get(caller_fn.path)
            if cctx is None:
                continue
            cenv = self._env_for(cctx, caller_fn.node, call)
            out |= self._resolve_axes(arg, cenv, depth + 1)
        if default is not None:
            out |= self._resolve_axes(
                default, _Env(param.ctx, None, None), depth + 1)
        return out

    def _param_default(self, fdef, name):
        a = fdef.args
        pos_params = a.posonlyargs + a.args
        n_def = len(a.defaults)
        for i, p in enumerate(pos_params):
            if p.arg == name:
                j = i - (len(pos_params) - n_def)
                return a.defaults[j] if j >= 0 else None
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == name and d is not None:
                return d
        return None


class _Param:
    """Marker: a name resolved to 'parameter NAME of FDEF'."""

    __slots__ = ("ctx", "fdef", "name")

    def __init__(self, ctx, fdef, name):
        self.ctx = ctx
        self.fdef = fdef
        self.name = name

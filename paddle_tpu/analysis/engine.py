"""Static-analysis engine: project-wide AST analysis over the framework source.

The reference Paddle enforces framework invariants two ways: sanitizer
flags checked at runtime (FLAGS_check_nan_inf, operator.cc:1311) and 161
IR pass files that *analyze* programs before running them. This package
applies the second idea to our own source: the invariants PRs 2-6
established by convention ("every eager collective rides
execute_collective", "every FLAGS_* read is declared", "framework threads
state their daemon contract") become machine-checked rules that run in
tier-1, so the next subsystem inherits them for free.

Since PR 11 the engine is INTERPROCEDURAL: before any checker runs, a
project-wide symbol table + call graph (``callgraph.ProjectIndex``) is
built over every analyzed file and handed to checkers through
``shared["project_index"]``, so a rule can ask "which functions are
transitively reachable from X" — the question the donation-safety
(D001/D002), SPMD-consistency (X004) and transitive trace-purity (T003)
rules exist to answer.

Pure stdlib by design: ``ast`` + ``json`` only, importable without jax so
``tools/check_static.py`` can gate CI in well under a second of import
cost.

Vocabulary:
- a *rule* is one invariant, identified by a short id ("C003");
- a *checker* is a module-level class contributing one or more rules;
- a *Finding* is one violation at one source location;
- the *baseline* is a committed allowlist of known findings — the gate
  fails on anything new AND on stale entries, so fixed findings must be
  removed from the baseline (it can only shrink).

Inline waivers: a line ending in ``# lint-ok: C003 <reason>`` suppresses
that rule on that line. Waivers are for invariants that are *intentionally*
broken at one site forever; transitional debt belongs in the baseline,
where the stale-entry check retires it. A waiver whose rule no longer
fires on its line is STALE and reported just like a stale baseline entry
(``Analysis.stale_waivers``) — dead waivers would otherwise silently
blind the rule if the code under them ever regresses.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import pickle
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .callgraph import ProjectIndex, build_index

__all__ = [
    "Finding", "Checker", "Analysis", "AstCache", "RULES", "load_baseline",
    "diff_against_baseline", "findings_to_baseline",
]

_WAIVER_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)")

# rule id -> (invariant, rationale); checkers register here at import
RULES: Dict[str, Tuple[str, str]] = {}


def register_rule(rule_id: str, invariant: str, rationale: str):
    RULES[rule_id] = (invariant, rationale)
    return rule_id


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``message`` is deterministic and line-number-free so the baseline
    match survives unrelated edits above the site; ``line`` is carried
    for human navigation only.
    """
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class FileContext:
    """Everything a checker gets for one file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        # declared inline waivers, parsed once: {line: {rule, ...}} — and
        # the subset a checker actually consulted, so unused (stale)
        # waivers can be reported after the run
        self.waiver_lines: Dict[int, set] = {}
        candidates = {}
        for i, text in enumerate(self.lines, start=1):
            m = _WAIVER_RE.search(text)
            if m:
                candidates[i] = {r.strip() for r in m.group(1).split(",")}
        if candidates:
            # confirm each candidate is a real COMMENT, not docstring prose
            # quoting the waiver syntax (tokenize only when needed)
            comment_lines = self._comment_lines(source)
            for i, rules in candidates.items():
                if comment_lines is None or i in comment_lines:
                    self.waiver_lines[i] = rules
        self.waivers_used: set = set()   # {(line, rule)}
        self._all_nodes = None

    def walk(self):
        """Every node of the tree, memoized — checkers iterate this
        instead of re-running ast.walk per sub-check (the full-tree walk
        dominated the project-wide pass's wall time)."""
        if self._all_nodes is None:
            self._all_nodes = list(ast.walk(self.tree))
        return self._all_nodes

    @staticmethod
    def _comment_lines(source: str) -> Optional[set]:
        """Line numbers carrying a ``# lint-ok`` comment token; None when
        tokenization fails (fall back to the permissive regex scan)."""
        import io
        import tokenize
        lines = None
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT and "lint-ok" in tok.string:
                    if lines is None:
                        lines = set()
                    lines.add(tok.start[0])
        except (tokenize.TokenizeError, SyntaxError, IndentationError,
                ValueError):
            return None
        return lines if lines is not None else set()

    def waived(self, rule: str, line: int) -> bool:
        rules = self.waiver_lines.get(line)
        if rules and rule in rules:
            self.waivers_used.add((line, rule))
            return True
        return False

    def stale_waivers(self) -> List[dict]:
        """Declared waivers whose rule never fired on their line — dead
        suppressions that must be deleted (mirrors baseline STALE)."""
        out = []
        for line in sorted(self.waiver_lines):
            for rule in sorted(self.waiver_lines[line]):
                if (line, rule) not in self.waivers_used:
                    out.append({"path": self.path, "line": line,
                                "rule": rule})
        return out


class Checker:
    """Base checker. Subclasses override ``check`` (and optionally
    ``collect`` for cross-file context gathered in pass 1)."""

    name = "checker"

    def collect(self, ctx: FileContext, shared: dict) -> None:
        """Pass 1: accumulate cross-file facts into ``shared``."""

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        """Pass 2: emit findings for one file."""
        return ()

    # helper: emit unless waived inline
    def finding(self, ctx: FileContext, rule: str, node: ast.AST,
                message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if ctx.waived(rule, line):
            return None
        return Finding(rule, ctx.path, line, message)


def _iter_py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


class AstCache:
    """Parsed-AST cache keyed by (path, mtime_ns, size): the project-wide
    pass re-reads all ~340 files on every run, but between runs almost
    none changed — pickling (source, tree) pairs cuts the cold-parse cost
    from the --changed-only hot path. Since PR 12 each entry also carries
    an ``extras`` dict for derived artifacts (the dataflow layer's CFGs,
    which reference the tree's own statement objects — identity survives
    the round-trip because tree and extras ride the same pickle).
    Corrupt/mismatched caches are ignored wholesale (never an error: the
    cache is an optimization)."""

    VERSION = f"2-{sys.version_info.major}.{sys.version_info.minor}"

    def __init__(self, path: str):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._entries: Dict[str, tuple] = {}
        self._dirty = False
        try:
            with open(path, "rb") as f:
                data = pickle.load(f)
            if data.get("version") == self.VERSION:
                self._entries = data["entries"]
        except (OSError, EOFError, pickle.UnpicklingError, AttributeError,
                KeyError, ValueError, ImportError):
            self._entries = {}

    def get(self, abspath: str, relpath: str):
        """(source, tree) for the file, parsed or from cache; None on
        read/parse failure (caller records the parse error itself)."""
        st = os.stat(abspath)
        key = (st.st_mtime_ns, st.st_size)
        hit = self._entries.get(relpath)
        if hit is not None and hit[0] == key:
            self.hits += 1
            return hit[1], hit[2]
        with open(abspath, "r", encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=relpath)
        self.misses += 1
        self._entries[relpath] = (key, src, tree, {})
        self._dirty = True
        return src, tree

    def extras(self, relpath: str) -> dict:
        """Mutable per-file extras dict (derived artifacts persisted with
        the parsed tree). Raises KeyError for files this run never
        parsed."""
        entry = self._entries[relpath]
        if len(entry) < 4:               # entry written before extras
            entry = entry + ({},)
            self._entries[relpath] = entry
        return entry[3]

    def mark_dirty(self):
        self._dirty = True

    def save(self):
        if not self._dirty:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "wb") as f:
                pickle.dump({"version": self.VERSION,
                             "entries": self._entries}, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass


class Analysis:
    """Project-wide run of all checkers over a source tree.

    Pass 0 builds the interprocedural ``ProjectIndex`` (symbol table +
    call graph) over every file; pass 1 lets checkers collect cross-file
    context (declared flags, metric schemas); pass 2 emits findings.
    ``rel_root`` controls how paths are reported (repo-relative, so the
    baseline is position-independent).

    After a run: ``self.index`` is the ProjectIndex, ``self.stale_waivers``
    the dead ``# lint-ok:`` comments (rule never fired on that line).
    """

    def __init__(self, checkers: Sequence[Checker], rel_root: str = ""):
        self.checkers = list(checkers)
        self.rel_root = rel_root
        self.parse_errors: List[str] = []
        self.index: Optional[ProjectIndex] = None
        self.stale_waivers: List[dict] = []
        self.dataflow = None          # DataflowIndex of the last run
        self.timings: Dict[str, float] = {}   # per-checker wall seconds
        self._cache: Optional[AstCache] = None

    def _context(self, abspath: str, relpath: str,
                 cache: Optional[AstCache]) -> Optional[FileContext]:
        try:
            if cache is not None:
                src, tree = cache.get(abspath, relpath)
            else:
                with open(abspath, "r", encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src, filename=relpath)
        except (OSError, SyntaxError, ValueError) as e:
            self.parse_errors.append(f"{relpath}: {e}")
            return None
        return FileContext(relpath, src, tree)

    def run_path(self, root: str,
                 cache: Optional[AstCache] = None) -> List[Finding]:
        root = os.path.abspath(root)
        rel_base = os.path.abspath(self.rel_root) if self.rel_root else \
            os.path.dirname(root)
        files = _iter_py_files(root)
        ctxs = []
        for p in files:
            rel = os.path.relpath(p, rel_base).replace(os.sep, "/")
            ctx = self._context(p, rel, cache)
            if ctx is not None:
                ctxs.append(ctx)
        self._cache = cache
        findings = self._run(ctxs)
        if cache is not None:
            # saved AFTER the run so checker-built extras (memoized CFGs)
            # persist alongside the trees they reference
            cache.save()
        return findings

    def run_sources(self, sources: Dict[str, str]) -> List[Finding]:
        """Analyze in-memory {relpath: source} — the test-fixture entry."""
        ctxs = []
        for rel, src in sorted(sources.items()):
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError as e:
                self.parse_errors.append(f"{rel}: {e}")
                continue
            ctxs.append(FileContext(rel, src, tree))
        return self._run(ctxs)

    def _run(self, ctxs: List[FileContext]) -> List[Finding]:
        import time

        from . import dataflow as dataflow_mod

        t0 = time.perf_counter()
        self.index = build_index(ctxs)
        self.dataflow = dataflow_mod.DataflowIndex(cache=self._cache)
        self.timings = {"index_build": time.perf_counter() - t0}
        shared: dict = {"project_index": self.index,
                        "dataflow": self.dataflow}
        for checker in self.checkers:
            t0 = time.perf_counter()
            for ctx in ctxs:
                checker.collect(ctx, shared)
            self.timings[checker.name] = time.perf_counter() - t0
        findings: List[Finding] = []
        for checker in self.checkers:
            t0 = time.perf_counter()
            for ctx in ctxs:
                findings.extend(f for f in checker.check(ctx, shared)
                                if f is not None)
            self.timings[checker.name] = round(
                self.timings.get(checker.name, 0.0)
                + (time.perf_counter() - t0), 4)
        self.timings["index_build"] = round(self.timings["index_build"], 4)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        self.stale_waivers = [w for ctx in ctxs
                              for w in ctx.stale_waivers()]
        return findings


# ---------------------------------------------------------------------------
# baseline: committed allowlist, matched on (rule, path, message) with
# multiplicity. New findings fail the gate; stale entries fail it too, so
# the baseline can only shrink as debt is paid down.
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data["entries"] if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path}: expected a list of entries")
    return entries


def findings_to_baseline(findings: Iterable[Finding],
                         reasons: Optional[Dict[str, str]] = None) -> dict:
    entries = []
    for f in findings:
        e = f.to_dict()
        if reasons and f.rule in reasons:
            e["reason"] = reasons[f.rule]
        entries.append(e)
    return {"entries": entries}


def diff_against_baseline(findings: Sequence[Finding],
                          baseline_entries: Sequence[dict]):
    """Returns (new_findings, stale_entries). Multiset match on
    (rule, path, message); ``line`` in the baseline is informational."""
    remaining: Dict[Tuple[str, str, str], int] = {}
    for e in baseline_entries:
        k = (e["rule"], e["path"], e["message"])
        remaining[k] = remaining.get(k, 0) + 1
    new = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    stale = []
    for e in baseline_entries:
        k = (e["rule"], e["path"], e["message"])
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            stale.append(e)
    return new, stale

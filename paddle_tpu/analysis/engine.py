"""Static-analysis engine: per-file AST visitors over the framework source.

The reference Paddle enforces framework invariants two ways: sanitizer
flags checked at runtime (FLAGS_check_nan_inf, operator.cc:1311) and 161
IR pass files that *analyze* programs before running them. This package
applies the second idea to our own source: the invariants PRs 2-6
established by convention ("every eager collective rides
execute_collective", "every FLAGS_* read is declared", "framework threads
state their daemon contract") become machine-checked rules that run in
tier-1, so the next subsystem inherits them for free.

Pure stdlib by design: ``ast`` + ``json`` only, importable without jax so
``tools/check_static.py`` can gate CI in well under a second of import
cost.

Vocabulary:
- a *rule* is one invariant, identified by a short id ("C003");
- a *checker* is a module-level class contributing one or more rules;
- a *Finding* is one violation at one source location;
- the *baseline* is a committed allowlist of known findings — the gate
  fails on anything new AND on stale entries, so fixed findings must be
  removed from the baseline (it can only shrink).

Inline waivers: a line ending in ``# lint-ok: C003 <reason>`` suppresses
that rule on that line. Waivers are for invariants that are *intentionally*
broken at one site forever; transitional debt belongs in the baseline,
where the stale-entry check retires it.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding", "Checker", "Analysis", "RULES", "load_baseline",
    "diff_against_baseline", "findings_to_baseline",
]

_WAIVER_RE = re.compile(r"#\s*lint-ok:\s*([A-Z]\d{3}(?:\s*,\s*[A-Z]\d{3})*)")

# rule id -> (invariant, rationale); checkers register here at import
RULES: Dict[str, Tuple[str, str]] = {}


def register_rule(rule_id: str, invariant: str, rationale: str):
    RULES[rule_id] = (invariant, rationale)
    return rule_id


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``message`` is deterministic and line-number-free so the baseline
    match survives unrelated edits above the site; ``line`` is carried
    for human navigation only.
    """
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}

    def __str__(self):
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


class FileContext:
    """Everything a checker gets for one file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()

    def waived(self, rule: str, line: int) -> bool:
        if 1 <= line <= len(self.lines):
            m = _WAIVER_RE.search(self.lines[line - 1])
            if m:
                waived = {r.strip() for r in m.group(1).split(",")}
                return rule in waived
        return False


class Checker:
    """Base checker. Subclasses override ``check`` (and optionally
    ``collect`` for cross-file context gathered in pass 1)."""

    name = "checker"

    def collect(self, ctx: FileContext, shared: dict) -> None:
        """Pass 1: accumulate cross-file facts into ``shared``."""

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        """Pass 2: emit findings for one file."""
        return ()

    # helper: emit unless waived inline
    def finding(self, ctx: FileContext, rule: str, node: ast.AST,
                message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if ctx.waived(rule, line):
            return None
        return Finding(rule, ctx.path, line, message)


def _iter_py_files(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


class Analysis:
    """Two-pass run of all checkers over a source tree.

    Pass 1 collects cross-file context (declared flags, metric schemas);
    pass 2 emits findings. ``rel_root`` controls how paths are reported
    (repo-relative, so the baseline is position-independent).
    """

    def __init__(self, checkers: Sequence[Checker], rel_root: str = ""):
        self.checkers = list(checkers)
        self.rel_root = rel_root
        self.parse_errors: List[str] = []

    def _context(self, abspath: str, relpath: str) -> Optional[FileContext]:
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=relpath)
        except (OSError, SyntaxError, ValueError) as e:
            self.parse_errors.append(f"{relpath}: {e}")
            return None
        return FileContext(relpath, src, tree)

    def run_path(self, root: str) -> List[Finding]:
        root = os.path.abspath(root)
        rel_base = os.path.abspath(self.rel_root) if self.rel_root else \
            os.path.dirname(root)
        files = _iter_py_files(root)
        ctxs = []
        for p in files:
            rel = os.path.relpath(p, rel_base).replace(os.sep, "/")
            ctx = self._context(p, rel)
            if ctx is not None:
                ctxs.append(ctx)
        return self._run(ctxs)

    def run_sources(self, sources: Dict[str, str]) -> List[Finding]:
        """Analyze in-memory {relpath: source} — the test-fixture entry."""
        ctxs = []
        for rel, src in sorted(sources.items()):
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError as e:
                self.parse_errors.append(f"{rel}: {e}")
                continue
            ctxs.append(FileContext(rel, src, tree))
        return self._run(ctxs)

    def _run(self, ctxs: List[FileContext]) -> List[Finding]:
        shared: dict = {}
        for checker in self.checkers:
            for ctx in ctxs:
                checker.collect(ctx, shared)
        findings: List[Finding] = []
        for checker in self.checkers:
            for ctx in ctxs:
                findings.extend(f for f in checker.check(ctx, shared)
                                if f is not None)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
        return findings


# ---------------------------------------------------------------------------
# baseline: committed allowlist, matched on (rule, path, message) with
# multiplicity. New findings fail the gate; stale entries fail it too, so
# the baseline can only shrink as debt is paid down.
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data["entries"] if isinstance(data, dict) else data
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path}: expected a list of entries")
    return entries


def findings_to_baseline(findings: Iterable[Finding],
                         reasons: Optional[Dict[str, str]] = None) -> dict:
    entries = []
    for f in findings:
        e = f.to_dict()
        if reasons and f.rule in reasons:
            e["reason"] = reasons[f.rule]
        entries.append(e)
    return {"entries": entries}


def diff_against_baseline(findings: Sequence[Finding],
                          baseline_entries: Sequence[dict]):
    """Returns (new_findings, stale_entries). Multiset match on
    (rule, path, message); ``line`` in the baseline is informational."""
    remaining: Dict[Tuple[str, str, str], int] = {}
    for e in baseline_entries:
        k = (e["rule"], e["path"], e["message"])
        remaining[k] = remaining.get(k, 0) + 1
    new = []
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
        else:
            new.append(f)
    stale = []
    for e in baseline_entries:
        k = (e["rule"], e["path"], e["message"])
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            stale.append(e)
    return new, stale

"""Signal-handler safety rule: handlers may only set flags/latches.

A Python-level signal handler runs on the main thread BETWEEN ARBITRARY
BYTECODES — in the middle of whatever the interrupted code was doing. A
handler that allocates heavily, acquires a lock the interrupted frame
already holds (logging's module lock is the classic), or performs I/O can
deadlock or corrupt the very state a preemption notice is supposed to
protect. The only safe body is the latch idiom
(robustness/preemption.py): assign the signum, set a threading.Event, and
let the training thread observe it at the next step boundary.

S002  a function registered as a handler via ``signal.signal(sig, fn)``
      in paddle_tpu may contain ONLY flag/latch statements: plain
      assignments of constants/names/attributes, ``<latch>.set()`` calls,
      ``pass``/``return``. Any other statement — logging, ``.acquire()``,
      allocation-heavy calls, I/O, checkpointing — is flagged. Lambdas
      registered inline are checked under the same contract.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional

from .engine import Checker, FileContext, Finding, register_rule

S002 = register_rule(
    "S002",
    "signal.signal handler bodies only set flags/latches (assignments of "
    "simple values and <latch>.set() calls; no allocation-heavy calls, "
    "lock acquisition, logging, or I/O)",
    "a Python signal handler interrupts arbitrary bytecode on the main "
    "thread; anything beyond a latch set can deadlock on a lock the "
    "interrupted frame holds (logging's, an allocator's) or corrupt the "
    "state the preemption notice exists to protect — do the real work at "
    "the next step boundary")

# call leaves a handler body MAY make: latch/flag set
_ALLOWED_CALL_LEAVES = {"set"}


def _call_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_simple_value(node: ast.AST) -> bool:
    """Constants, names, attribute reads, and tuples thereof — values a
    latch assignment may store without allocation-heavy work."""
    if isinstance(node, (ast.Constant, ast.Name, ast.Attribute)):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_simple_value(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_simple_value(node.operand)
    return False


def _bad_statement(stmt: ast.stmt) -> Optional[ast.AST]:
    """The first sub-node of `stmt` that breaks the latch-only contract,
    or None when the statement is allowed."""
    if isinstance(stmt, (ast.Pass, ast.Global, ast.Nonlocal)):
        return None
    if isinstance(stmt, ast.Return):
        if stmt.value is None or _is_simple_value(stmt.value):
            return None
        return stmt.value
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        value = stmt.value
        if value is None or _is_simple_value(value):
            return None
        return value
    if isinstance(stmt, ast.Expr):
        v = stmt.value
        if isinstance(v, ast.Constant):  # docstring
            return None
        if isinstance(v, ast.Call) and not v.args and not v.keywords \
                and _call_leaf(v) in _ALLOWED_CALL_LEAVES:
            return None
        return v
    return stmt


def _check_body(body: List[ast.stmt]) -> Optional[ast.AST]:
    for stmt in body:
        bad = _bad_statement(stmt)
        if bad is not None:
            return bad
    return None


def _is_signal_signal(call: ast.Call) -> bool:
    """``signal.signal(...)`` (or a bare ``signal(...)`` imported name)
    with two arguments — the registration this rule keys on."""
    if len(call.args) < 2:
        return False
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "signal":
        recv = f.value
        return isinstance(recv, ast.Name) and recv.id == "signal"
    return isinstance(f, ast.Name) and f.id == "signal"


class SignalSafetyChecker(Checker):
    name = "signal_safety"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        # pass A: every function/lambda in the file by name (methods too —
        # the registration site names `self._handler`; the attribute leaf
        # resolves to the module's FunctionDef of that name)
        defs: Dict[str, ast.AST] = {}
        for node in ctx.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        out = []
        seen = set()
        for node in ctx.walk():
            if not (isinstance(node, ast.Call) and _is_signal_signal(node)):
                continue
            handler = node.args[1]
            if isinstance(handler, ast.Lambda):
                bad = (None if _is_simple_value(handler.body)
                       or (isinstance(handler.body, ast.Call)
                           and not handler.body.args
                           and not handler.body.keywords
                           and _call_leaf(handler.body)
                           in _ALLOWED_CALL_LEAVES)
                       else handler.body)
                name, anchor = "<lambda>", (bad or handler)
            else:
                hname = None
                if isinstance(handler, ast.Attribute):
                    hname = handler.attr
                elif isinstance(handler, ast.Name):
                    hname = handler.id
                fn = defs.get(hname) if hname else None
                if fn is None:
                    continue  # imported/dynamic handler: not analyzable here
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                bad = _check_body(fn.body)
                name, anchor = fn.name, (bad or fn)
            if bad is None:
                continue
            f = self.finding(
                ctx, S002, anchor,
                f"signal handler {name!r} does more than set flags/latches "
                f"— move the work to a step-boundary check "
                f"(robustness.PreemptionHandler.should_stop)")
            if f is not None:
                out.append(f)
        return out

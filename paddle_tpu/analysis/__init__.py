"""Framework static-analysis suite + runtime sanitizers (PR 7, PR 11).

Static half: a pure-stdlib AST analysis engine (engine.py), since PR 11
INTERPROCEDURAL — callgraph.py builds a project-wide symbol table + call
graph before any checker runs, so rules can follow calls across files —
with these checker families:

- concurrency.py        C001 daemon= explicit, C002 acquire/release
                        discipline, C003 no silent except-swallows,
                        C004 lock-owning modules guard global writes
- collective_safety.py  X001 raw lax collectives stay in distributed/
                        (baseline ZERO: model code uses the sanctioned
                        collective.in_trace_psum/pmax helpers),
                        X002 eager collectives ride execute_collective,
                        X003 no rank-conditional collective branches,
                        X004 no rank-conditional branch TRANSITIVELY
                        reaching a collective through the call graph
- trace_purity.py       T001 no wall-clock/host-RNG/host-sync in traced fns,
                        T002 grad_comm wire codecs stay pure jnp (the
                        eager/traced shared-verbatim contract, ISSUE 8),
                        T003 no impurity through ANY call chain from a
                        traced fn (confident edges; _in_trace()-guarded
                        dual-path functions are trusted boundaries)
- registry_drift.py     R001 FLAGS_* declared in framework/flags.py,
                        R002 metric label schemas consistent
- resource_release.py   F001 path-aware resource release over the CFG —
                        acquired lane-gathered buffers release on EVERY
                        path to function exit incl. early-return and
                        exception edges (supersedes the syntactic S001,
                        kept as a waiver alias); F002 future-await —
                        BucketFuture/GatherFuture/sync_async handles are
                        awaited, drained, or escape on every path;
                        F005 span close — begin_span() results reach
                        end_span() (or escape) on every path, exception
                        edges included (ISSUE 18 trace spans)
- commit_order.py       F003 checkpoint commit functions write the
                        MANIFEST last: the manifest write post-dominates
                        every payload write on the normal-flow CFG (the
                        PR-2 crash-safety invariant, machine-checked)
- mesh_axes.py          X005 mesh-axis validity — axis names that
                        resolvably reach psum/all_gather/constrain/
                        shard_map sites (reaching-defs + one-hop call
                        graph) exist in the mesh-axis registry
- signal_safety.py      S002 signal.signal handler bodies only set
                        flags/latches (the async-signal-safe preemption
                        latch contract, ISSUE 10)
- donation.py           D001 no read of a donated binding after the
                        donating jit call, D002 donated-buffer outputs
                        ordered before batch outputs in the return tuple
                        (the PR-8 TrainStep donation-alias bug, ISSUE 11)
- kernel_gates.py       K001 every pl.pallas_call resolves interpret=
                        through the target_platform() seam — no literal
                        True/False, no missing kwarg (ISSUE 13: CPU
                        tier-1 can never silently pin a TPU-only path)

Since PR 12 the engine is additionally FLOW-SENSITIVE: dataflow.py builds
per-function CFGs (if/while/for/try/except/finally/with/return/raise/
break/continue, exception edges into handlers and finallys, panic edges
for unprotected raises) and runs a generic worklist solver (forward +
backward, union or intersection meet) with packaged reaching-definitions,
liveness, and post-dominator instances — memoized per function in
``shared["dataflow"]`` and persisted in the parsed-AST pickle cache.

Runtime half: lock_order.py — a lock-order witness (lockdep/TSan style)
that wraps framework locks under FLAGS_lock_order_check and reports
ABBA-inversion cycles, plus the post-suite thread-leak check — and
host_sync.py (ISSUE 11) — patches the device→host sync points under
FLAGS_host_sync_check to record blocking syncs inside train-step spans.

Gate: ``tools/check_static.py --baseline tools/static_baseline.json``
runs everything over paddle_tpu/ in tier-1; new findings exit 1, stale
baseline entries OR stale inline waivers exit 2. ``--changed-only`` /
``--sarif`` / the parsed-AST cache serve CI; ``tools/bench_gate.py
--static-budget`` pins the full-run wall time.
"""
from __future__ import annotations

from . import callgraph  # noqa: F401  (pure stdlib)
from . import dataflow  # noqa: F401  (pure stdlib)
from . import host_sync  # noqa: F401  (standalone-safe: lazy jax import)
from . import lock_order  # noqa: F401  (standalone-safe, pure stdlib)
from .callgraph import ProjectIndex, build_index
from .collective_safety import CollectiveSafetyChecker
from .commit_order import CommitOrderChecker
from .concurrency import ConcurrencyChecker
from .donation import DonationSafetyChecker
from .engine import (Analysis, AstCache, Checker, Finding, RULES,
                     diff_against_baseline, findings_to_baseline,
                     load_baseline)
from .kernel_gates import KernelGateChecker
from .mesh_axes import MeshAxisChecker
from .registry_drift import RegistryDriftChecker
from .resource_release import ResourceReleaseChecker
from .signal_safety import SignalSafetyChecker
from .trace_purity import TracePurityChecker

__all__ = [
    "Analysis", "AstCache", "Checker", "Finding", "ProjectIndex", "RULES",
    "build_index", "default_checkers", "analyze_tree", "analyze_sources",
    "diff_against_baseline", "findings_to_baseline", "load_baseline",
    "callgraph", "dataflow", "host_sync", "lock_order",
]


def default_checkers():
    return [
        ConcurrencyChecker(),
        CollectiveSafetyChecker(),
        TracePurityChecker(),
        RegistryDriftChecker(),
        ResourceReleaseChecker(),
        CommitOrderChecker(),
        MeshAxisChecker(),
        SignalSafetyChecker(),
        DonationSafetyChecker(),
        KernelGateChecker(),
    ]


def analyze_tree(root: str, rel_root: str = ""):
    """All default checkers over a source tree; returns sorted Findings."""
    return Analysis(default_checkers(), rel_root=rel_root).run_path(root)


def analyze_sources(sources):
    """All default checkers over in-memory {path: source} fixtures."""
    return Analysis(default_checkers()).run_sources(sources)

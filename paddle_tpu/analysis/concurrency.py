"""Concurrency-discipline rules over the framework's threads and locks.

PRs 2-6 grew ~15 locks and a dozen background threads (CollectiveLane,
async checkpoint, HangDetector, exposition HTTP, PS server, elastic
heartbeat). These rules pin the conventions that kept them safe:

C001  every ``threading.Thread(...)`` states ``daemon=`` explicitly —
      the default (inherit from creator) silently flips a thread's
      shutdown contract when the creating context changes.
C002  ``lock.acquire()`` as a bare statement must sit in a try whose
      ``finally`` releases the same lock (or just use ``with``) — an
      exception mid-critical-section otherwise leaks a held lock and the
      next acquirer deadlocks.
C003  ``except Exception: pass`` (or broader) swallows framework faults
      silently; narrow the type or record the fault.
C004  a module that owns a module-level lock must hold it when its
      functions assign module globals — a lock next to unguarded global
      writes is usually a forgotten critical section.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .engine import Checker, FileContext, Finding, register_rule

C001 = register_rule(
    "C001",
    "threading.Thread call sites pass daemon= explicitly",
    "daemon defaults to the creating thread's flag, so omitting it makes "
    "the shutdown contract depend on who called the constructor")
C002 = register_rule(
    "C002",
    "bare lock.acquire() statements are paired with release() in a finally "
    "(or rewritten as `with lock:`)",
    "an exception between acquire and release leaks a held lock; the next "
    "acquirer blocks forever")
C003 = register_rule(
    "C003",
    "no `except Exception:`/bare-except whose body is only pass",
    "framework faults must not disappear silently — narrow the exception "
    "type or record the fault to observability.events.get_event_log()")
C004 = register_rule(
    "C004",
    "modules owning a module-level lock hold it while assigning module "
    "globals inside functions",
    "a module-level lock advertises shared mutable state; a `global` write "
    "outside `with <lock>:` is usually a forgotten critical section")

_LOCK_FACTORIES = {"Lock", "RLock"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_thread_call(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d is not None and (d == "Thread" or d.endswith(".Thread"))


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d is None:
        return False
    leaf = d.rsplit(".", 1)[-1]
    return leaf in _LOCK_FACTORIES


class ConcurrencyChecker(Checker):
    name = "concurrency"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        out: List[Optional[Finding]] = []
        out.extend(self._check_threads(ctx))
        out.extend(self._check_acquire(ctx))
        out.extend(self._check_swallow(ctx))
        out.extend(self._check_global_mutation(ctx))
        return [f for f in out if f is not None]

    # -- C001 ---------------------------------------------------------------
    def _check_threads(self, ctx: FileContext):
        for node in ctx.walk():
            if isinstance(node, ast.Call) and _is_thread_call(node):
                kwargs = {k.arg for k in node.keywords if k.arg}
                has_splat = any(k.arg is None for k in node.keywords)
                if "daemon" not in kwargs and not has_splat:
                    yield self.finding(
                        ctx, C001, node,
                        "threading.Thread(...) without explicit daemon=")

    # -- C002 ---------------------------------------------------------------
    def _check_acquire(self, ctx: FileContext):
        # single recursive descent from the module body, threading the set
        # of lock names released in an enclosing `finally`
        for stmt in ctx.tree.body:
            yield from self._acquire_in_stmt(ctx, stmt, enclosing_final=())

    def _acquire_in_stmt(self, ctx, stmt, enclosing_final):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            target = self._acquire_target(stmt.value)
            if target is not None and target not in enclosing_final:
                yield self.finding(
                    ctx, C002, stmt,
                    f"bare {target}.acquire() with no matching release() in "
                    "a finally block — use `with` or try/finally")
        for child in ast.iter_child_nodes(stmt):
            finals = enclosing_final
            if isinstance(stmt, ast.Try):
                released = self._released_targets(stmt.finalbody)
                finals = enclosing_final + tuple(released)
            if isinstance(child, ast.stmt):
                yield from self._acquire_in_stmt(ctx, child, finals)
            else:
                # expressions can nest statements only via lambda bodies
                # (no statements there) — nothing to recurse into
                continue

    @staticmethod
    def _acquire_target(call: ast.Call) -> Optional[str]:
        if isinstance(call.func, ast.Attribute) and call.func.attr == "acquire":
            return _dotted(call.func.value)
        return None

    @staticmethod
    def _released_targets(finalbody) -> Set[str]:
        rel = set()
        for n in finalbody:
            for sub in ast.walk(n):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"):
                    d = _dotted(sub.func.value)
                    if d:
                        rel.add(d)
        return rel

    # -- C003 ---------------------------------------------------------------
    def _check_swallow(self, ctx: FileContext):
        for node in ctx.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if all(self._is_noop(s) for s in node.body):
                caught = "bare except" if node.type is None else \
                    f"except {_dotted(node.type)}"
                yield self.finding(
                    ctx, C003, node,
                    f"{caught}: pass — swallows faults silently; narrow the "
                    "type or record to the event log")

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(_dotted(e) in ("Exception", "BaseException")
                       for e in type_node.elts)
        return _dotted(type_node) in ("Exception", "BaseException")

    @staticmethod
    def _is_noop(stmt) -> bool:
        if isinstance(stmt, ast.Pass):
            return True
        return (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and (stmt.value.value is Ellipsis
                     or isinstance(stmt.value.value, str)))

    # -- C004 ---------------------------------------------------------------
    def _check_global_mutation(self, ctx: FileContext):
        module_locks = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_locks.add(t.id)
        if not module_locks:
            return
        for node in ctx.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function_globals(
                    ctx, node, module_locks)

    def _check_function_globals(self, ctx, fn, module_locks):
        declared = set()
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Global):
                declared.update(stmt.names)
        if not declared:
            return
        # every assignment to a declared-global name must sit under a
        # `with <module lock>:`
        yield from self._scan_for_unlocked(
            ctx, fn, fn.body, declared, module_locks, locked=False)

    def _scan_for_unlocked(self, ctx, fn, body, declared, locks, locked):
        for stmt in body:
            now_locked = locked
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    d = _dotted(item.context_expr)
                    if d is None and isinstance(item.context_expr, ast.Call):
                        d = _dotted(item.context_expr.func)
                    if d and d.rsplit(".", 1)[-1] in locks:
                        now_locked = True
            if not now_locked:
                targets = []
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    targets = [stmt.target]
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared:
                        yield self.finding(
                            ctx, C004, stmt,
                            f"module global '{t.id}' assigned in "
                            f"{fn.name}() without holding a module lock "
                            f"({', '.join(sorted(locks))})")
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            if isinstance(e, ast.Name) and e.id in declared:
                                yield self.finding(
                                    ctx, C004, stmt,
                                    f"module global '{e.id}' assigned in "
                                    f"{fn.name}() without holding a module "
                                    f"lock ({', '.join(sorted(locks))})")
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested scopes have their own global decls
                if isinstance(child, ast.stmt):
                    self_gen = self._scan_for_unlocked(
                        ctx, fn, [child], declared, locks, now_locked)
                    yield from self_gen
                elif hasattr(child, "body") and isinstance(
                        getattr(child, "body", None), list):
                    yield from self._scan_for_unlocked(
                        ctx, fn, child.body, declared, locks, now_locked)

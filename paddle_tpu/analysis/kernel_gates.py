"""Kernel-gate rule: pallas interpret mode resolves through the target seam.

Every pallas kernel in this codebase must run compiled (Mosaic) on a TPU
compile target and interpreted everywhere else — and "compile target" is
``framework/target.py``'s ``target_platform()`` question, NOT
``jax.default_backend()`` (under AOT compilation arrays live on CPU while
the target is a described TPU slice). The convention so far was manual:
each kernel module defines ``_interpret()`` calling ``target_platform()``
and every ``pl.pallas_call`` passes ``interpret=_interpret()``.

K001 machine-checks it: a ``pallas_call`` site with a literal
``interpret=True`` would pin the interpreter even when compiling for TPU
(a silent ~100x slowdown shipped to production), a literal
``interpret=False`` or a MISSING ``interpret=`` would pin Mosaic so CPU
tier-1 either crashes or — worse — quietly skips the kernel path forever.
The rule demands the keyword be present and resolve, through same-file
function calls (up to 3 hops), to something that references
``target_platform``. Expressions it cannot prove are findings too: the
gate is cheap to satisfy (call the module-local ``_interpret()``) and the
failure mode it prevents is expensive to debug.

K001  every ``pl.pallas_call`` passes ``interpret=`` as an expression
      that resolves through the shared ``target_platform()`` seam — no
      literal True/False, no missing keyword, no unresolvable helper.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional

from .engine import Checker, FileContext, Finding, register_rule

K001 = register_rule(
    "K001",
    "pl.pallas_call sites resolve interpret= through the "
    "target_platform() seam (no literal True/False)",
    "a literal pins one execution mode for every platform: "
    "interpret=True ships the interpreter to TPU production, "
    "interpret=False (or omitting the kwarg) breaks CPU tier-1 or "
    "silently parks the kernel path untested")

_MAX_HOPS = 3


def _is_pallas_call(node: ast.Call) -> bool:
    """``pl.pallas_call(...)`` / ``pallas.pallas_call(...)`` / bare
    ``pallas_call(...)`` after a from-import."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr == "pallas_call"
    return isinstance(f, ast.Name) and f.id == "pallas_call"


def _mentions_target_platform(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "target_platform":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "target_platform":
            return True
    return False


class KernelGateChecker(Checker):
    name = "kernel_gates"

    # -- pass 2 --------------------------------------------------------------
    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        sites = [n for n in ctx.walk()
                 if isinstance(n, ast.Call) and _is_pallas_call(n)]
        if not sites:
            return []
        local_fns = self._local_functions(ctx)
        out: List[Optional[Finding]] = []
        for call in sites:
            kw = next((k for k in call.keywords if k.arg == "interpret"),
                      None)
            if kw is None:
                if any(k.arg is None for k in call.keywords):
                    continue  # **splat: not statically resolvable
                out.append(self.finding(
                    ctx, K001, call,
                    "pallas_call without interpret= — the site silently "
                    "pins compiled (Mosaic) mode on every platform; pass "
                    "interpret=<module>._interpret() resolving through "
                    "target_platform()"))
                continue
            if isinstance(kw.value, ast.Constant):
                out.append(self.finding(
                    ctx, K001, kw.value,
                    f"pallas_call with literal interpret="
                    f"{kw.value.value!r} — resolve it through the "
                    f"target_platform() seam instead"))
                continue
            if not self._resolves_through_seam(kw.value, local_fns):
                out.append(self.finding(
                    ctx, K001, kw.value,
                    "pallas_call interpret= expression does not "
                    "resolvably reach target_platform() (checked the "
                    "expression and same-file callees, 3 hops) — route "
                    "it through the shared seam"))
        return [f for f in out if f is not None]

    # -- resolution ----------------------------------------------------------
    @staticmethod
    def _local_functions(ctx: FileContext) -> Dict[str, ast.AST]:
        return {n.name: n for n in ctx.walk()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _resolves_through_seam(self, expr: ast.AST,
                               local_fns: Dict[str, ast.AST]) -> bool:
        """True when ``expr`` mentions target_platform directly, or calls
        same-file functions whose bodies (transitively, bounded hops) do."""
        seen: set = set()
        frontier = [expr]
        for _ in range(_MAX_HOPS + 1):
            nxt = []
            for node in frontier:
                if _mentions_target_platform(node):
                    return True
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = None
                    if isinstance(sub.func, ast.Name):
                        name = sub.func.id
                    elif isinstance(sub.func, ast.Attribute) and \
                            isinstance(sub.func.value, ast.Name) and \
                            sub.func.value.id == "self":
                        name = sub.func.attr
                    if name and name in local_fns and name not in seen:
                        seen.add(name)
                        nxt.append(local_fns[name])
            if not nxt:
                return False
            frontier = nxt
        return False

"""Flow-sensitive dataflow engine: per-function CFGs + a worklist solver.

PR 7 gave the suite per-file AST pattern rules; PR 11 made them
interprocedural (callgraph.py). Both rungs are PATH-BLIND: S001 could only
ask "is there a release inside *a* finally somewhere in this module", not
"is the release reachable from *every* exit of the acquiring function",
and nothing could prove the PR-2 crash-safety invariant (MANIFEST written
last) holds on every commit path. This module supplies the missing layer:

- :func:`build_cfg` — a control-flow graph for one ``def``, one node per
  statement, covering if/while(+else)/for(+else)/try/except/else/finally/
  with/return/raise/break/continue and generator functions. Edges carry a
  kind:

  * ``flow``  — normal sequential/branch flow;
  * ``exc``   — exception flow INTO a handler or finally block (every
    statement under a ``try`` may raise; explicit ``raise`` always does);
  * ``panic`` — exception flow OUT of the function from a statement not
    protected by any try (the process-failure edge). Cleanup regions
    (``finally`` bodies, except-handler bodies) are trusted not to fail
    and get no panic edges — otherwise no release discipline could ever
    be proven (the release call itself "might raise").

  ``return`` routes through every enclosing ``finally`` before reaching
  EXIT (so return-in-finally and finally-swallows-exception shapes are
  modeled); break/continue route through finallys inner to their loop.
  The graph is a sound over-approximation: every executable path exists
  in it, plus some infeasible ones — rules built on it may under-report,
  never mis-prove.

- :func:`solve` — a generic worklist solver, forward or backward,
  configurable meet (union / intersection) and edge-kind filter, with an
  iteration bound that turns non-convergence into a loud error instead
  of a hang. Facts are hashable; transfer functions are arbitrary.

- Packaged instances every checker can reuse through
  ``shared["dataflow"]`` (a :class:`DataflowIndex`, memoized per
  function and persisted in the parsed-AST pickle cache):
  :func:`reaching_definitions`, :func:`liveness`, and
  :func:`postdominators` (intersection meet — the F003 manifest-last
  proof is "the MANIFEST write post-dominates every payload write").

Pure stdlib (``ast`` only), like the rest of the static half, so
``tools/check_static.py`` stays importable without jax.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, \
    Set, Tuple

__all__ = [
    "CFG", "CFGNode", "ConvergenceError", "DataflowIndex", "build_cfg",
    "liveness", "postdominators", "reaching_definitions", "solve",
]

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

FLOW, EXC, PANIC = "flow", "exc", "panic"
ALL_KINDS = frozenset((FLOW, EXC, PANIC))
NO_PANIC = frozenset((FLOW, EXC))
FLOW_ONLY = frozenset((FLOW,))


class ConvergenceError(RuntimeError):
    """The worklist exceeded its iteration bound — a transfer function is
    not monotone (or the bound is mis-set); never a silent hang."""


class CFGNode:
    """One statement (or the synthetic entry/exit) of a function CFG."""

    __slots__ = ("idx", "stmt", "kind", "line", "label", "succs", "preds")

    def __init__(self, idx: int, stmt: Optional[ast.stmt], kind: str,
                 label: str):
        self.idx = idx
        self.stmt = stmt
        self.kind = kind                 # "entry" | "exit" | "stmt"
        self.line = getattr(stmt, "lineno", 0)
        self.label = label
        self.succs: List[Tuple[int, str]] = []   # (node idx, edge kind)
        self.preds: List[Tuple[int, str]] = []

    def __repr__(self):
        return f"<CFGNode {self.idx} {self.label}@{self.line}>"


class CFG:
    """nodes[0] is ENTRY, nodes[1] is EXIT."""

    ENTRY, EXIT = 0, 1

    def __init__(self, func: ast.AST):
        self.func = func
        self.name = getattr(func, "name", "<fn>")
        self.nodes: List[CFGNode] = []
        # every expression/sub-statement id() -> owning stmt node idx
        # (lets a checker map an arbitrary ast.Call back onto the graph)
        self.owner: Dict[int, int] = {}

    # -- queries -------------------------------------------------------------
    def node_of(self, ast_node) -> Optional[int]:
        return self.owner.get(id(ast_node))

    def succs(self, idx: int, kinds: FrozenSet[str] = ALL_KINDS):
        return [s for s, k in self.nodes[idx].succs if k in kinds]

    def preds(self, idx: int, kinds: FrozenSet[str] = ALL_KINDS):
        return [p for p, k in self.nodes[idx].preds if k in kinds]

    def reachable_from(self, idx: int,
                       kinds: FrozenSet[str] = ALL_KINDS) -> Set[int]:
        seen, stack = {idx}, [idx]
        while stack:
            for s in self.succs(stack.pop(), kinds):
                if s not in seen:
                    seen.add(s)
                    stack.append(s)
        return seen

    def find_path(self, src: int, dst: int, avoid: Optional[Set[int]] = None,
                  kinds: FrozenSet[str] = ALL_KINDS) -> Optional[List[int]]:
        """Shortest src→dst path (BFS, deterministic order), optionally
        avoiding a node set — the "show me the leaking path" query."""
        avoid = avoid or set()
        if src in avoid:
            return None
        prev: Dict[int, int] = {src: -1}
        queue = [src]
        while queue:
            cur = queue.pop(0)
            if cur == dst:
                path = [cur]
                while prev[path[-1]] != -1:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            for s in sorted(self.succs(cur, kinds)):
                if s not in prev and s not in avoid:
                    prev[s] = cur
                    queue.append(s)
        return None

    def describe_path(self, path: Iterable[int]) -> str:
        out = []
        for idx in path:
            n = self.nodes[idx]
            if n.kind == "stmt":
                out.append(f"{n.label}@L{n.line}")
            else:
                out.append(n.kind)
        return " -> ".join(out)


def _stmt_label(stmt: ast.stmt) -> str:
    return type(stmt).__name__.lower()


class _LoopCtx:
    __slots__ = ("head", "breaks")

    def __init__(self, head: int):
        self.head = head
        self.breaks: List[int] = []      # nodes whose flow goes after-loop


class _TryCtx:
    """One enclosing ``try`` during construction. ``mode`` is where we are
    relative to it: "body" (handlers + finally apply), "recover" (handler
    or else body: only the finally applies), "finally" (neither — the
    frame is transparent)."""

    __slots__ = ("has_handlers", "has_finally", "mode", "raisers",
                 "deferred")

    def __init__(self, has_handlers: bool, has_finally: bool):
        self.has_handlers = has_handlers
        self.has_finally = has_finally
        self.mode = "body"
        self.raisers: List[int] = []     # nodes whose exc flow enters here
        # abnormal exits that must traverse the finally before continuing:
        # list of (node_idx_or_None, kind) where kind in
        # {"return","break","continue","exc"}; node None marks a kind
        # re-routed from an inner finally's exit frontier
        self.deferred: List[Tuple[Optional[int], str]] = []


class _Builder:
    def __init__(self, func):
        self.cfg = CFG(func)
        self._new(None, "entry")             # idx 0
        self._new(None, "exit")              # idx 1
        self.loops: List[_LoopCtx] = []
        self.tries: List[_TryCtx] = []
        self.in_cleanup = 0                  # finally/handler depth
        # try-stack depth at each loop entry: break/continue traverse only
        # the finallys of frames opened INSIDE their loop
        self._loop_try_base: List[int] = []

    # -- graph primitives ----------------------------------------------------
    def _new(self, stmt, kind, label="") -> int:
        n = CFGNode(len(self.cfg.nodes), stmt, kind,
                    label or (kind if stmt is None else _stmt_label(stmt)))
        self.cfg.nodes.append(n)
        return n.idx

    def _edge(self, src: int, dst: int, kind: str = FLOW):
        pair = (dst, kind)
        if pair not in self.cfg.nodes[src].succs:
            self.cfg.nodes[src].succs.append(pair)
            self.cfg.nodes[dst].preds.append((src, kind))

    def _connect(self, frontier: List[Tuple[int, str]], dst: int):
        for src, kind in frontier:
            self._edge(src, dst, kind)

    def _own(self, stmt, idx: int):
        """Map every sub-node of ``stmt`` (headers only for compound
        statements; nested defs excluded) onto its CFG node."""
        headers = [stmt]
        if isinstance(stmt, (ast.If, ast.While)):
            headers = [stmt.test]
        elif isinstance(stmt, ast.For):
            headers = [stmt.target, stmt.iter]
        elif isinstance(stmt, ast.Try):
            headers = []
        elif isinstance(stmt, ast.With):
            headers = [i for item in stmt.items
                       for i in (item.context_expr, item.optional_vars)
                       if i is not None]
        elif isinstance(stmt, ast.ExceptHandler):
            headers = [stmt.type] if stmt.type is not None else []
        for h in headers:
            stack = [h]
            while stack:
                node = stack.pop()
                self.cfg.owner.setdefault(id(node), idx)
                if not isinstance(node, _DEFS):
                    stack.extend(ast.iter_child_nodes(node))
        self.cfg.owner.setdefault(id(stmt), idx)

    # -- abnormal-exit routing ----------------------------------------------
    def _route(self, src: int, kind: str):
        """Send an abnormal exit (return/break/continue/exc) outward from
        ``src`` through the context stacks to its target, stopping at the
        first enclosing finally (which re-dispatches it after running)."""
        if kind in ("break", "continue"):
            if not self.loops:
                return                       # malformed; ignore
            base = self._loop_try_base[-1]
            for t in reversed(self.tries[base:]):
                if t.mode != "finally" and t.has_finally:
                    t.deferred.append((src, kind))
                    return
            loop = self.loops[-1]
            if kind == "break":
                loop.breaks.append(src)
            else:
                self._edge(src, loop.head, FLOW)
            return
        for t in reversed(self.tries):
            if t.mode == "finally":
                continue
            if kind == "exc" and t.mode == "body" and t.has_handlers:
                t.raisers.append(src)
                return
            if t.has_finally:
                t.deferred.append((src, kind))
                return
            if kind == "exc" and t.mode == "recover":
                continue                      # propagate past this frame
        if kind == "return":
            self._edge(src, CFG.EXIT, FLOW)
        else:                                 # unprotected exception
            if not self.in_cleanup:
                self._edge(src, CFG.EXIT, PANIC)

    # -- statement dispatch --------------------------------------------------
    def build(self) -> CFG:
        frontier = [(CFG.ENTRY, FLOW)]
        frontier = self._body(self.cfg.func.body, frontier)
        self._connect(frontier, CFG.EXIT)
        # ENTRY owns the args (parameter "definitions")
        args = getattr(self.cfg.func, "args", None)
        if args is not None:
            for a in ast.walk(args):
                self.cfg.owner.setdefault(id(a), CFG.ENTRY)
        return self.cfg

    def _body(self, stmts, frontier):
        for stmt in stmts:
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _simple(self, stmt, frontier, may_raise=True):
        idx = self._new(stmt, "stmt")
        self._own(stmt, idx)
        self._connect(frontier, idx)
        if may_raise:
            self._route(idx, "exc")
        return idx

    def _stmt(self, stmt, frontier):
        if isinstance(stmt, ast.If):
            return self._if(stmt, frontier)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, frontier)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            idx = self._simple(stmt, frontier)
            return self._body(stmt.body, [(idx, FLOW)])
        if isinstance(stmt, ast.Return):
            idx = self._simple(stmt, frontier, may_raise=False)
            self._route(idx, "return")
            return []
        if isinstance(stmt, ast.Raise):
            idx = self._new(stmt, "stmt")
            self._own(stmt, idx)
            self._connect(frontier, idx)
            self._route_raise(idx)
            return []
        if isinstance(stmt, ast.Break):
            idx = self._simple(stmt, frontier, may_raise=False)
            self._route(idx, "break")
            return []
        if isinstance(stmt, ast.Continue):
            idx = self._simple(stmt, frontier, may_raise=False)
            self._route(idx, "continue")
            return []
        # simple statement (incl. nested def/class headers, yield exprs)
        idx = self._simple(stmt, frontier)
        return [(idx, FLOW)]

    def _route_raise(self, idx: int):
        """Explicit raise: like an implicit exc, but reaches EXIT (as
        PANIC) even outside cleanup regions when unprotected."""
        for t in reversed(self.tries):
            if t.mode == "finally":
                continue
            if t.mode == "body" and t.has_handlers:
                t.raisers.append(idx)
                return
            if t.has_finally:
                t.deferred.append((idx, "exc"))
                return
        self._edge(idx, CFG.EXIT, PANIC)

    def _if(self, stmt, frontier):
        idx = self._simple(stmt, frontier)
        out = self._body(stmt.body, [(idx, FLOW)])
        if stmt.orelse:
            out = out + self._body(stmt.orelse, [(idx, FLOW)])
        else:
            out = out + [(idx, FLOW)]
        return out

    def _loop(self, stmt, frontier):
        head = self._simple(stmt, frontier)
        loop = _LoopCtx(head)
        self.loops.append(loop)
        self._loop_try_base.append(len(self.tries))
        body_out = self._body(stmt.body, [(head, FLOW)])
        self._connect(body_out, head)            # back edge
        self._loop_try_base.pop()
        self.loops.pop()
        # natural loop exit: test false / iterator exhausted — absent for
        # a literal `while True:` (its only exits are breaks)
        infinite = (isinstance(stmt, ast.While)
                    and isinstance(stmt.test, ast.Constant)
                    and bool(stmt.test.value))
        out = [] if infinite else [(head, FLOW)]
        if stmt.orelse:
            out = self._body(stmt.orelse, out)
        out = out + [(b, FLOW) for b in loop.breaks]
        return out

    def _try(self, stmt, frontier):
        ctx = _TryCtx(has_handlers=bool(stmt.handlers),
                      has_finally=bool(stmt.finalbody))
        self.tries.append(ctx)
        body_out = self._body(stmt.body, frontier)
        if stmt.orelse:
            ctx.mode = "recover"
            body_out = self._body(stmt.orelse, body_out)

        # handler subgraphs: every raiser in the body may enter every
        # handler (type matching is over-approximated)
        ctx.mode = "recover"
        handler_out: List[Tuple[int, str]] = []
        self.in_cleanup += 1
        for h in stmt.handlers:
            h_idx = self._new(h, "stmt", label="except")
            self._own(h, h_idx)
            for r in ctx.raisers:
                self._edge(r, h_idx, EXC)
            handler_out += self._body(h.body, [(h_idx, FLOW)])
        self.in_cleanup -= 1

        self.tries.pop()
        if not stmt.finalbody:
            # an uncaught exception in the body propagates outward: model
            # by letting raisers also route past this frame
            if not stmt.handlers:
                for r in ctx.raisers:
                    self._route(r, "exc")
            else:
                # a raiser whose exception matches no handler propagates;
                # over-approximate only for bare raisers that are
                # themselves `raise` statements (cheap and rare) — plain
                # statements are assumed covered by the handlers
                pass
            return body_out + handler_out

        # finally: built once; entered from normal completion, every
        # handler exit, every unmatched/in-handler raiser, and every
        # deferred abnormal exit
        ctx.mode = "finally"
        self.in_cleanup += 1
        fin_entry_frontier = list(body_out) + list(handler_out)
        fin_entry_frontier += [(r, EXC) for r in ctx.raisers
                               if not stmt.handlers]
        fin_entry_frontier += [(n, EXC if k == "exc" else FLOW)
                               for n, k in ctx.deferred if n is not None]
        if not fin_entry_frontier:
            fin_entry_frontier = frontier     # degenerate: empty body
        fin_out = self._body(stmt.finalbody, fin_entry_frontier)
        self.in_cleanup -= 1

        # re-dispatch the deferred exits from the finally's frontier
        kinds_pending = {k for _, k in ctx.deferred}
        if stmt.handlers:
            pass
        elif ctx.raisers:
            kinds_pending.add("exc")
        for n, _k in fin_out:
            for kind in sorted(kinds_pending):
                self._route(n, kind)
        # normal continuation exists iff the body/handlers could complete
        if body_out or handler_out or not kinds_pending:
            return fin_out
        return []


def build_cfg(func: ast.AST) -> CFG:
    """CFG for one function/async-function def (body is walked; nested
    defs become single statement nodes with their own CFGs on demand)."""
    return _Builder(func).build()


# ---------------------------------------------------------------------------
# generic worklist solver
# ---------------------------------------------------------------------------

def solve(cfg: CFG, *, direction: str,
          transfer: Callable[[int, FrozenSet], FrozenSet],
          meet: str = "union",
          boundary: FrozenSet = frozenset(),
          kinds: FrozenSet[str] = ALL_KINDS,
          universe: Optional[FrozenSet] = None,
          max_iters: Optional[int] = None) -> Dict[int, Tuple[FrozenSet,
                                                              FrozenSet]]:
    """Iterate ``transfer`` to a fixed point over ``cfg``.

    direction: "forward" (IN = meet over preds' OUT) or "backward"
    (IN = meet over succs' OUT). meet: "union" or "intersect"
    ("intersect" requires ``universe``, the top element). ``boundary``
    seeds ENTRY (forward) / EXIT (backward). Returns {idx: (in, out)}.
    Raises :class:`ConvergenceError` past ``max_iters`` worklist pops
    (default: generous in graph size — real lattices converge far
    earlier)."""
    n = len(cfg.nodes)
    fwd = direction == "forward"
    start = CFG.ENTRY if fwd else CFG.EXIT
    if max_iters is None:
        max_iters = 64 * n * n + 4096
    if meet == "intersect" and universe is None:
        raise ValueError("intersect meet needs a universe (top) set")
    top = universe if meet == "intersect" else frozenset()

    ins: Dict[int, FrozenSet] = {i: top for i in range(n)}
    outs: Dict[int, FrozenSet] = {i: top for i in range(n)}
    ins[start] = boundary
    outs[start] = transfer(start, boundary)

    edges_in = (cfg.preds if fwd else cfg.succs)
    edges_out = (cfg.succs if fwd else cfg.preds)

    work = list(range(n))
    pops = 0
    while work:
        pops += 1
        if pops > max_iters:
            raise ConvergenceError(
                f"dataflow did not converge on {cfg.name} "
                f"({n} nodes, {pops} pops)")
        idx = work.pop(0)
        sources = edges_in(idx, kinds)
        if idx == start:
            new_in = boundary
        elif not sources:
            new_in = top if meet == "intersect" else frozenset()
        else:
            acc = None
            for s in sources:
                acc = outs[s] if acc is None else (
                    acc | outs[s] if meet == "union" else acc & outs[s])
            new_in = acc
        new_out = transfer(idx, new_in)
        if new_in == ins[idx] and new_out == outs[idx] and pops > n:
            continue
        changed = new_out != outs[idx]
        ins[idx], outs[idx] = new_in, new_out
        if changed:
            for s in edges_out(idx, kinds):
                if s not in work:
                    work.append(s)
    return {i: (ins[i], outs[i]) for i in range(n)}


# ---------------------------------------------------------------------------
# packaged instances
# ---------------------------------------------------------------------------

def _assigned_names(stmt: ast.stmt) -> Set[str]:
    """Names (re)bound by executing this one statement (compound headers
    only — a For binds its target, its body belongs to other nodes)."""
    out: Set[str] = set()

    def targets(t):
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets(t)
        value_walk = [stmt.value]
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(stmt.target, ast.Name):
            out.add(stmt.target.id)
        value_walk = [stmt.value] if stmt.value else []
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets(stmt.target)
        value_walk = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                targets(item.optional_vars)
        value_walk = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.ExceptHandler):
        if stmt.name:
            out.add(stmt.name)
        value_walk = []
    elif isinstance(stmt, _DEFS) or isinstance(stmt, ast.ClassDef):
        out.add(stmt.name)
        value_walk = []
    elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
        for a in stmt.names:
            out.add((a.asname or a.name).split(".")[0])
        value_walk = []
    else:
        value_walk = [stmt]
    # walrus targets anywhere in the evaluated expressions
    for root in value_walk:
        for n in ast.walk(root):
            if isinstance(n, ast.NamedExpr) and isinstance(n.target,
                                                           ast.Name):
                out.add(n.target.id)
    return out


def _node_gen(cfg: CFG, idx: int) -> Set[str]:
    node = cfg.nodes[idx]
    if node.kind == "entry":
        args = getattr(cfg.func, "args", None)
        if args is None:
            return set()
        names = [a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return set(names)
    if node.stmt is None:
        return set()
    return _assigned_names(node.stmt)


class ReachingDefs:
    """Forward may-analysis: facts are ``(name, def_node_idx)``."""

    def __init__(self, cfg: CFG):
        self.cfg = cfg
        gens = {i: _node_gen(cfg, i) for i in range(len(cfg.nodes))}
        self._gens = gens

        def transfer(idx, inset):
            g = gens[idx]
            if not g:
                return inset
            kept = frozenset(f for f in inset if f[0] not in g)
            return kept | frozenset((name, idx) for name in g)

        boundary = frozenset()
        self.sets = solve(cfg, direction="forward", transfer=transfer,
                          boundary=boundary, kinds=NO_PANIC)

    def defs_at(self, idx: int, name: str) -> List[int]:
        """Def-site node idxs of ``name`` reaching the ENTRY of node
        ``idx`` (ENTRY idx 0 = a parameter binding)."""
        return sorted(d for n, d in self.sets[idx][0] if n == name)


def reaching_definitions(cfg: CFG) -> ReachingDefs:
    return ReachingDefs(cfg)


def _used_names(stmt: ast.stmt) -> Set[str]:
    roots: List[ast.AST]
    if isinstance(stmt, (ast.If, ast.While)):
        roots = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.Try):
        roots = []
    else:
        roots = [stmt]
    out: Set[str] = set()
    for root in roots:
        stack = [root]
        while stack:
            n = stack.pop()
            if isinstance(n, _DEFS):
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
            stack.extend(ast.iter_child_nodes(n))
    return out


def liveness(cfg: CFG) -> Dict[int, Tuple[FrozenSet, FrozenSet]]:
    """Backward may-analysis: which names are live (read later on some
    path) — {idx: (live_out, live_in)} in solver orientation (the solver's
    IN of a backward problem is the meet over successors)."""
    uses = {i: _used_names(cfg.nodes[i].stmt)
            if cfg.nodes[i].stmt is not None else set()
            for i in range(len(cfg.nodes))}
    gens = {i: _node_gen(cfg, i) for i in range(len(cfg.nodes))}

    def transfer(idx, live_out):
        return frozenset((set(live_out) - gens[idx]) | uses[idx])

    return solve(cfg, direction="backward", transfer=transfer,
                 boundary=frozenset(), kinds=NO_PANIC)


def postdominators(cfg: CFG,
                   kinds: FrozenSet[str] = FLOW_ONLY) -> Dict[int,
                                                              FrozenSet]:
    """{idx: frozenset of node idxs that post-dominate idx} over the
    given edge kinds. Backward intersection meet; nodes that cannot reach
    EXIT over ``kinds`` post-dominate vacuously (their set is the
    universe) — callers asking "does X post-dominate Y" on a Y that never
    reaches EXIT normally get True, which is the right answer for the
    manifest rule (a path that never commits violates nothing)."""
    universe = frozenset(range(len(cfg.nodes)))

    def transfer(idx, inset):
        return frozenset(inset | {idx})

    sets = solve(cfg, direction="backward", transfer=transfer,
                 meet="intersect", universe=universe,
                 boundary=frozenset(), kinds=kinds)
    return {i: sets[i][1] for i in range(len(cfg.nodes))}


# ---------------------------------------------------------------------------
# per-run memoization (exposed to checkers as shared["dataflow"])
# ---------------------------------------------------------------------------

class DataflowIndex:
    """Memoized CFG/analysis access for every checker in one run.

    CFGs are additionally persisted into the parsed-AST pickle cache
    (``AstCache`` extras): a CFG references the statement objects of its
    tree, and both live in the same pickle, so identity survives the
    round-trip. Keys are ``qual@lineno`` within a file — invalidated
    together with the tree on any file change (same mtime+size key)."""

    def __init__(self, cache=None):
        self._cache = cache
        self._cfgs: Dict[int, CFG] = {}
        self._rd: Dict[int, ReachingDefs] = {}
        self._live: Dict[int, dict] = {}
        self._pdom: Dict[Tuple[int, FrozenSet[str]], dict] = {}
        self.built = 0
        self.from_cache = 0

    def _extras(self, path: Optional[str]):
        if self._cache is None or path is None:
            return None
        try:
            return self._cache.extras(path).setdefault("cfgs", {})
        except (AttributeError, KeyError):
            return None

    def cfg(self, func: ast.AST, path: Optional[str] = None) -> CFG:
        key = id(func)
        hit = self._cfgs.get(key)
        if hit is not None:
            return hit
        store = self._extras(path)
        ckey = f"{getattr(func, 'name', '<fn>')}@{getattr(func, 'lineno', 0)}"
        if store is not None:
            cached = store.get(ckey)
            # identity check: the cached CFG must reference THIS tree's
            # def object (a re-parse invalidates the pairing)
            if cached is not None and cached.func is func:
                self._cfgs[key] = cached
                self.from_cache += 1
                return cached
        g = build_cfg(func)
        self._cfgs[key] = g
        self.built += 1
        if store is not None:
            store[ckey] = g
            self._cache.mark_dirty()
        return g

    def reaching(self, func: ast.AST,
                 path: Optional[str] = None) -> ReachingDefs:
        key = id(func)
        if key not in self._rd:
            self._rd[key] = reaching_definitions(self.cfg(func, path))
        return self._rd[key]

    def live(self, func: ast.AST, path: Optional[str] = None):
        key = id(func)
        if key not in self._live:
            self._live[key] = liveness(self.cfg(func, path))
        return self._live[key]

    def postdom(self, func: ast.AST, path: Optional[str] = None,
                kinds: FrozenSet[str] = FLOW_ONLY):
        key = (id(func), kinds)
        if key not in self._pdom:
            self._pdom[key] = postdominators(self.cfg(func, path), kinds)
        return self._pdom[key]

"""Resource-release rule: lane-launched gathers must free on all paths.

ZeRO-3 (distributed/sharding/stage3.py) materializes FULL parameter
buckets by launching all_gathers on a ``CollectiveLane`` — transient
buffers that are `world`× the at-rest footprint. The whole memory win
rests on every gathered buffer being released again, including when the
use scope exits via an exception: a leak here is silent (training keeps
working, HBM quietly fills with full-size parameters) until an OOM far
from the cause.

S001  a module that launches bucket gathers on a CollectiveLane (a
      ``*.submit(...)`` on a lane plus calls to a gather-acquiring method)
      must contain a release call (``free_bucket`` / ``free_gathered`` /
      ``release_gathered`` / ``free_all``) inside a ``finally:`` block —
      the one construct reachable from both the normal and the exception
      exit of the use scope. The stage-3 store satisfies it with
      ``materialize()``'s try/finally; new lane gather clients must ship
      the same discipline.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .engine import Checker, FileContext, Finding, register_rule

S001 = register_rule(
    "S001",
    "lane-launched gathers release gathered buffers on all paths "
    "(free call inside a finally block)",
    "a gathered parameter bucket is world-times the at-rest footprint; "
    "without a release reachable from the exception exit of the use scope "
    "the ZeRO-3 memory win silently leaks away until an OOM far from the "
    "cause")

# gather-acquiring methods: transition a bucket to the materialized state
_ACQUIRE = {"ensure_gathered", "gather_bucket", "prefetch_bucket"}
# releasing methods: transition back to at-rest
_RELEASE = {"free_bucket", "free_gathered", "release_gathered", "free_all"}


def _attr_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_lane_submit(call: ast.Call) -> bool:
    """``<recv>.submit(...)`` where the receiver names a lane
    (``self._lane.submit``, ``lane.submit``, ``gather_lane.submit``)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"):
        return False
    recv = call.func.value
    name = None
    if isinstance(recv, ast.Attribute):
        name = recv.attr
    elif isinstance(recv, ast.Name):
        name = recv.id
    return name is not None and "lane" in name.lower()


class ResourceReleaseChecker(Checker):
    name = "resource_release"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        lane_submits = False
        acquires: List[ast.Call] = []
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            if _is_lane_submit(node):
                lane_submits = True
            leaf = _attr_leaf(node)
            if leaf in _ACQUIRE:
                acquires.append(node)
        if not (lane_submits and acquires):
            return ()
        # all-paths release: a _RELEASE call somewhere inside a finally
        # block (ast.Try.finalbody) of this module
        for node in ctx.walk():
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    if (isinstance(sub, ast.Call)
                            and _attr_leaf(sub) in _RELEASE):
                        return ()
        anchor = min(acquires, key=lambda c: getattr(c, "lineno", 1))
        f = self.finding(
            ctx, S001, anchor,
            "module launches bucket gathers on a CollectiveLane but no "
            "free/release call sits inside a finally block — gathered "
            "full-size buffers leak on exception exits")
        return [f] if f is not None else ()

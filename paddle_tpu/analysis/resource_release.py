"""Path-aware resource-release + future-await rules (F001/F002).

ZeRO-3 (distributed/sharding/stage3.py) materializes FULL parameter
buckets by launching all_gathers on a ``CollectiveLane`` — transient
buffers `world`× the at-rest footprint — and hands out future objects
(``GatherFuture``/``BucketFuture``) for in-flight collectives. Two leak
shapes follow:

F001  **path-aware release** (supersedes the syntactic S001): in a module
      that launches bucket gathers on a CollectiveLane, a function that
      both acquires gathered buffers (``ensure_gathered``/``gather_bucket``)
      and releases them (``free_bucket``/``free_gathered``/
      ``release_gathered``/``free_all``) must release on EVERY CFG path
      from the acquire to the function exit — early returns, exception
      edges into handlers/finallys, and unprotected-raise (panic) exits
      included. The finding names the leaking path. A module that
      acquires but never releases anywhere keeps S001's module-level
      verdict. Functions that acquire without releasing locally transfer
      ownership (the store pattern: the bucket state lives on ``self``
      and a later hook frees it) and are out of scope by design.

      Proof machinery: forward gen/kill over ``dataflow.build_cfg`` with
      ALL edge kinds (a statement outside any try can still raise — only
      a ``finally``/handler makes the release reachable from that exit,
      which is exactly the S001 contract, now *proven* per path instead
      of pattern-matched). Release kills are argument-matched
      (``free_bucket(b.index)`` releases what ``ensure_gathered(b.index)``
      acquired) and lifted to enclosing loop heads, so a
      release-loop-in-finally discharges an acquire-loop-in-body.

F002  **future-await**: a ``BucketFuture``/``GatherFuture``/``sync_async``
      handle bound to a local that reaches function exit on some path
      without being awaited (``wait``/``result``/``sync``), drained
      (``abandon``/``flush``), or escaping (returned / yielded / stored /
      passed along — any later use of the name counts) is a silent-hang
      or lane-slot leak; a maker call whose result is discarded outright
      is flagged immediately. Panic edges are excluded: an unprotected
      exception abandons the process, not a lane slot.

F004  **drain re-admission** (ISSUE 17): ``drained = <engine>.drain()``
      fences a serving replica and hands back its in-flight requests —
      requests the PR-14 zero-lost contract says must be re-admitted
      (``requeue_front``/``submit``/``requeue``/``readmit``) or
      explicitly retired with the queue (``close``) on EVERY non-panic
      CFG path to function exit. The fleet controller's scale_down and
      the watchdog's evict both churn replicas on policy decisions now,
      so "the drained list reaches exit unforwarded on the early-return
      branch" is precisely a lost-request bug — proven per path, like
      F002. Returning/yielding the list or storing it on an attribute
      transfers ownership; a ``.drain()`` whose result is discarded
      outright is flagged immediately.

F005  **span close** (ISSUE 18): a trace span opened with
      ``begin_span(...)`` (observability/tracing.py) and bound to a local
      must reach ``end_span(<that local>)`` on EVERY CFG path to function
      exit — exception edges included, exactly F001's acquire/release
      proof with begin/end as the pair. An un-ended span is never
      committed to the trace store or the flight recorder, so the
      request's timeline silently loses the hop precisely when it
      crashed — the moment the trace exists for. Returning/yielding the
      span or storing it on an attribute transfers ownership; the
      ``with tracer.span(...)`` context manager discharges itself (its
      finally ends the span); a ``begin_span`` result discarded outright
      is flagged immediately. Lifecycle edges should prefer the one-shot
      ``record_span`` — which opens nothing and is out of scope here.

F006  **standby lifecycle** (ISSUE 19): a standby replica acquired for
      warm handoff (``sb = <set>.acquire_standby(...)``) is an engine +
      KV pool OUTSIDE the replica set — nobody evicts it, nobody drains
      it. On EVERY non-panic CFG path to function exit it must either be
      promoted into the set (``promote``/``swap_in``), torn down
      (``stop``/``abandon``), or escape (returned/yielded/stored — the
      new owner carries the obligation). The either/or matters exactly
      on the branches it is easiest to miss: the boot-budget timeout
      path and the exception path out of ``warm()``. A maker call whose
      result is discarded outright is flagged immediately. Panic edges
      are excluded like F002/F004 (an unprotected exception abandons the
      frame's owner too); discharge sites match the standby name as the
      call's RECEIVER (``sb.promote()``) as well as an argument.

S001 stays registered as the superseded alias: ``# lint-ok: S001``
waivers still suppress the F001 finding at the same site.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import dataflow
from .callgraph import walk_stop_at_defs
from .engine import Checker, FileContext, Finding, register_rule

F001 = register_rule(
    "F001",
    "lane-gathered buffers are released on every CFG path from acquire to "
    "function exit (early returns and exception edges included)",
    "a gathered parameter bucket is world-times the at-rest footprint; a "
    "single early-return or exception path that skips the release silently "
    "leaks it until an OOM far from the cause — the path-aware upgrade of "
    "S001's syntactic finally check")
F002 = register_rule(
    "F002",
    "a BucketFuture/GatherFuture/sync_async handle is awaited "
    "(wait/result/sync), drained (abandon/flush) or escapes on every path "
    "to function exit",
    "a future that silently reaches exit unconsumed is a lane-slot leak: "
    "its collective may still be running, its error is never surfaced, "
    "and a later barrier hangs with no owner")
F004 = register_rule(
    "F004",
    "a drained request list (<engine>.drain()) is re-admitted "
    "(requeue_front/submit/requeue/readmit), retired with the queue "
    "(close), returned, or stored on every non-panic path to function "
    "exit",
    "drain() hands back live in-flight requests under the zero-lost "
    "contract; a path that drops the drained list on the floor loses "
    "accepted user requests with no error anywhere — the exact bug class "
    "replica eviction and policy-driven scale_down must never reintroduce")
F005 = register_rule(
    "F005",
    "a span opened with begin_span() reaches end_span() on every CFG path "
    "from open to function exit (exception edges included), or is "
    "returned/stored; `with tracer.span(...)` discharges itself",
    "an open span that never reaches end_span() is never committed to the "
    "trace store or flight-recorder ring: the request's timeline silently "
    "drops the hop exactly where it crashed — close in a finally or use "
    "the span() context manager")
F006 = register_rule(
    "F006",
    "a standby replica acquired for warm handoff (acquire_standby()) is "
    "promoted (promote/swap_in), torn down (stop/abandon), or escapes "
    "(returned/stored) on every non-panic CFG path to function exit",
    "a dropped standby is an engine + KV pool outside the replica set — "
    "no watchdog evicts it, no drain path fences it; the warm-handoff "
    "either/or (swap in or tear down) must hold on the boot-budget "
    "timeout and exception branches, precisely where it is easiest to "
    "forget")
S001 = register_rule(
    "S001",
    "(superseded by F001) lane-launched gathers release gathered buffers "
    "on all paths — the syntactic finally check is now the path-aware "
    "F001 proof; S001 waivers still apply at F001 sites",
    "kept as a live alias so existing '# lint-ok: S001' waivers and "
    "historical baselines keep their meaning")

# gather-acquiring methods: transition a bucket to the materialized state.
# prefetch_bucket is deliberately absent: its future is stored on the
# store (ownership transfer) and freed by the post-hook/free_bucket path.
_ACQUIRE = {"ensure_gathered", "gather_bucket"}
# releasing methods: transition back to at-rest
_RELEASE = {"free_bucket", "free_gathered", "release_gathered", "free_all"}
# future-handle constructors / producers tracked by F002
_MAKERS = {"BucketFuture", "GatherFuture", "sync_async"}
_AWAITS = {"wait", "result", "sync"}
_DRAINS = {"abandon", "flush"}
# F004: the drain maker and what discharges its obligation
_DRAIN_MAKER = "drain"
_READMITS = {"requeue_front", "submit", "requeue", "readmit"}
_RETIRES = {"close"}
# F005: the span open/close pair (observability/tracing.py)
_SPAN_OPEN = {"begin_span"}
_SPAN_CLOSE = {"end_span"}
# F006: the standby maker and its either/or discharge sets
_STANDBY_MAKER = "acquire_standby"
_PROMOTES = {"promote", "swap_in"}
_TEARDOWNS = {"stop", "abandon"}

_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _attr_leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _is_lane_submit(call: ast.Call) -> bool:
    """``<recv>.submit(...)`` where the receiver names a lane
    (``self._lane.submit``, ``lane.submit``, ``gather_lane.submit``)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"):
        return False
    recv = call.func.value
    name = None
    if isinstance(recv, ast.Attribute):
        name = recv.attr
    elif isinstance(recv, ast.Name):
        name = recv.id
    return name is not None and "lane" in name.lower()


def _arg_key(call: ast.Call) -> str:
    """Resource identity of an acquire/release call: the dump of its first
    positional argument ("*" = matches anything when absent)."""
    if call.args:
        try:
            return ast.dump(call.args[0])
        except Exception:
            return "*"
    return "*"


def _kills_fact(kill_key: str, fact_key: str) -> bool:
    return kill_key == "*" or fact_key == "*" or kill_key == fact_key


class ResourceReleaseChecker(Checker):
    """F001 + F002 over per-function CFGs (shared["dataflow"])."""

    name = "resource_release"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        calls = [n for n in ctx.walk() if isinstance(n, ast.Call)]
        lane = any(_is_lane_submit(c) for c in calls)
        acquires = [c for c in calls if _attr_leaf(c) in _ACQUIRE]
        releases = [c for c in calls if _attr_leaf(c) in _RELEASE]
        makers = [c for c in calls if _attr_leaf(c) in _MAKERS]
        drains = [c for c in calls if _attr_leaf(c) == _DRAIN_MAKER
                  and isinstance(c.func, ast.Attribute) and not c.args]
        spans = [c for c in calls if _attr_leaf(c) in _SPAN_OPEN]
        standbys = [c for c in calls if _attr_leaf(c) == _STANDBY_MAKER]
        if not ((lane and acquires) or makers or drains or spans
                or standbys):
            return ()
        df: dataflow.DataflowIndex = shared["dataflow"]
        out: List[Finding] = []
        if lane and acquires and not releases:
            # S001's module-level verdict, kept: gathers with no release
            # anywhere cannot be discharged on any path
            anchor = min(acquires, key=lambda c: getattr(c, "lineno", 1))
            f = self._finding_aliased(
                ctx, anchor,
                "module launches bucket gathers on a CollectiveLane but "
                "contains no free/release call at all — gathered full-size "
                "buffers leak on every exit path")
            if f is not None:
                out.append(f)
        for node in ctx.walk():
            if not isinstance(node, _FN_DEFS):
                continue
            if lane and acquires and releases:
                out.extend(self._check_release_paths(ctx, df, node))
            if makers:
                out.extend(self._check_future_await(ctx, df, node))
            if drains:
                out.extend(self._check_drain_readmit(ctx, df, node))
            if spans:
                out.extend(self._check_span_close(ctx, df, node))
            if standbys:
                out.extend(self._check_standby_lifecycle(ctx, df, node))
        return out

    def _finding_aliased(self, ctx, node, message) -> Optional[Finding]:
        """An F001 finding suppressible by either a F001 or an S001
        (legacy alias) waiver on the line."""
        line = getattr(node, "lineno", 1)
        if ctx.waived(F001, line) or ctx.waived(S001, line):
            return None
        return Finding(F001, ctx.path, line, message)

    # ------------------------------------------------------------------ F001
    def _own_calls(self, cfg: dataflow.CFG, fdef) -> List[Tuple[ast.Call,
                                                                int]]:
        """(call, owning node idx) for calls of THIS function's body —
        calls inside nested defs have no owner in this CFG and are
        checked when their own def is visited."""
        out = []
        for sub in ast.walk(fdef):
            if isinstance(sub, ast.Call):
                idx = cfg.node_of(sub)
                if idx is not None:
                    out.append((sub, idx))
        return out

    def _loop_kills(self, cfg: dataflow.CFG) -> Dict[int, Set[str]]:
        """Release arg-keys lifted to enclosing loop-head nodes: a loop
        whose body releases discharges the obligation on the loop's own
        zero-iteration path too (the finally-loop-over-buckets shape —
        CFG paths cannot see that the two loops iterate in lockstep)."""
        kills: Dict[int, Set[str]] = {}
        for n in cfg.nodes:
            if n.stmt is None or not isinstance(n.stmt, (ast.For, ast.While,
                                                         ast.AsyncFor)):
                continue
            for sub in walk_stop_at_defs(n.stmt):
                if isinstance(sub, ast.Call) and _attr_leaf(sub) in _RELEASE:
                    kills.setdefault(n.idx, set()).add(_arg_key(sub))
        return kills

    def _check_release_paths(self, ctx, df, fdef) -> Iterable[Finding]:
        acquire_calls, release_calls = [], []
        for sub in walk_stop_at_defs(fdef):
            if isinstance(sub, ast.Call):
                leaf = _attr_leaf(sub)
                if leaf in _ACQUIRE:
                    acquire_calls.append(sub)
                elif leaf in _RELEASE:
                    release_calls.append(sub)
        if not (acquire_calls and release_calls):
            return ()
        cfg = df.cfg(fdef, ctx.path)
        gen: Dict[int, Set[Tuple[int, str]]] = {}
        for call in acquire_calls:
            idx = cfg.node_of(call)
            if idx is not None:
                gen.setdefault(idx, set()).add((idx, _arg_key(call)))
        kills: Dict[int, Set[str]] = self._loop_kills(cfg)
        for call in release_calls:
            idx = cfg.node_of(call)
            if idx is not None:
                kills.setdefault(idx, set()).add(_arg_key(call))
        if not gen:
            return ()

        def transfer(idx, inset):
            ks = kills.get(idx)
            cur = inset
            if ks:
                cur = frozenset(f for f in cur
                                if not any(_kills_fact(k, f[1])
                                           for k in ks))
            g = gen.get(idx)
            return cur | frozenset(g) if g else cur

        sets = dataflow.solve(cfg, direction="forward", transfer=transfer,
                              kinds=dataflow.ALL_KINDS)
        leaked = sets[dataflow.CFG.EXIT][0]
        out = []
        for acq_idx, key in sorted(leaked):
            avoid = {i for i, ks in kills.items()
                     if any(_kills_fact(k, key) for k in ks)}
            path = cfg.find_path(acq_idx, dataflow.CFG.EXIT, avoid=avoid)
            desc = cfg.describe_path(path) if path else "<path unavailable>"
            node = cfg.nodes[acq_idx]
            f = self._finding_aliased(
                ctx, node.stmt,
                f"{cfg.name}(): gathered bucket acquired here can reach "
                f"function exit without a free/release on the path "
                f"[{desc}] — add a try/finally (or release on the "
                f"early-exit branch)")
            if f is not None:
                out.append(f)
        return out

    # ------------------------------------------------------------------ F002
    def _check_future_await(self, ctx, df, fdef) -> Iterable[Finding]:
        maker_assigns: List[Tuple[str, ast.Assign]] = []
        discarded: List[ast.Call] = []
        has_drain = False
        for sub in walk_stop_at_defs(fdef):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _attr_leaf(sub.value) in _MAKERS:
                maker_assigns.append((sub.targets[0].id, sub))
            elif isinstance(sub, ast.Expr) and isinstance(sub.value,
                                                          ast.Call) \
                    and _attr_leaf(sub.value) in _MAKERS:
                discarded.append(sub.value)
            elif isinstance(sub, ast.Call) and _attr_leaf(sub) in _DRAINS:
                has_drain = True
        out = []
        for call in discarded:
            f = self.finding(
                ctx, F002, call,
                f"{fdef.name}(): {_attr_leaf(call)}(...) result discarded — "
                f"the future handle (its error, its lane slot) is "
                f"unreachable; await it, store it, or abandon() the "
                f"communicator")
            if f is not None:
                out.append(f)
        if not maker_assigns or has_drain:
            return out
        cfg = df.cfg(fdef, ctx.path)
        gen: Dict[int, Set[Tuple[str, int]]] = {}
        tracked: Set[str] = set()
        for var, assign in maker_assigns:
            idx = cfg.node_of(assign)
            if idx is not None:
                gen.setdefault(idx, set()).add((var, idx))
                tracked.add(var)
        if not gen:
            return out
        # any later use of the name kills the obligation: awaits consume
        # it, returns/yields/stores/calls make it someone else's — what
        # remains is "bound, then forgotten on this path"
        uses: Dict[int, Set[str]] = {}
        for n in cfg.nodes:
            if n.stmt is None:
                continue
            names = dataflow._used_names(n.stmt) & tracked
            if names:
                uses[n.idx] = names

        def transfer(idx, inset):
            used = uses.get(idx)
            cur = inset
            if used:
                cur = frozenset(f for f in cur if f[0] not in used)
            g = gen.get(idx)
            if g:
                cur = frozenset(f for f in cur
                                if f[0] not in {v for v, _ in g})
                cur = cur | frozenset(g)
            return cur

        sets = dataflow.solve(cfg, direction="forward", transfer=transfer,
                              kinds=dataflow.NO_PANIC)
        leaked = sets[dataflow.CFG.EXIT][0]
        for var, node_idx in sorted(leaked, key=lambda f: (f[1], f[0])):
            avoid = {i for i, names in uses.items() if var in names}
            path = cfg.find_path(node_idx, dataflow.CFG.EXIT, avoid=avoid,
                                 kinds=dataflow.NO_PANIC)
            desc = cfg.describe_path(path) if path else "<path unavailable>"
            f = self.finding(
                ctx, F002, cfg.nodes[node_idx].stmt,
                f"{fdef.name}(): future handle '{var}' reaches function "
                f"exit un-awaited and un-escaped on the path [{desc}] — "
                f"wait()/result() it, return it, or store it before every "
                f"exit")
            if f is not None:
                out.append(f)
        return out

    # ------------------------------------------------------------------ F004
    def _drain_discharges(self, stmt, tracked: Set[str]
                          ) -> Tuple[Set[str], bool]:
        """(names discharged by this statement, discharge-everything?).

        A drained list is discharged by: appearing in the arguments of a
        re-admission call; the owning queue being close()d (shutdown —
        the requests are retired WITH the queue); being returned/yielded
        (the caller owns it now); or being stored into an attribute/
        subscript (escapes to an object that outlives the frame)."""
        names: Set[str] = set()
        kill_all = False
        for sub in walk_stop_at_defs(stmt):
            if isinstance(sub, ast.Call):
                leaf = _attr_leaf(sub)
                if leaf in _RETIRES:
                    kill_all = True
                elif leaf in _READMITS:
                    for arg in list(sub.args) + [k.value
                                                 for k in sub.keywords]:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name):
                                names.add(n.id)
            elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and sub.value is not None:
                for n in ast.walk(sub.value):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(sub, ast.Assign):
                stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in sub.targets)
                if stores:
                    for n in ast.walk(sub.value):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        return names & tracked if tracked else set(), kill_all

    def _check_drain_readmit(self, ctx, df, fdef) -> Iterable[Finding]:
        drain_assigns: List[Tuple[str, ast.Assign]] = []
        discarded: List[ast.Call] = []
        for sub in walk_stop_at_defs(fdef):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _attr_leaf(sub.value) == _DRAIN_MAKER \
                    and isinstance(sub.value.func, ast.Attribute) \
                    and not sub.value.args:
                drain_assigns.append((sub.targets[0].id, sub))
            elif isinstance(sub, ast.Expr) and isinstance(sub.value,
                                                          ast.Call) \
                    and _attr_leaf(sub.value) == _DRAIN_MAKER \
                    and isinstance(sub.value.func, ast.Attribute) \
                    and not sub.value.args:
                discarded.append(sub.value)
        out = []
        for call in discarded:
            f = self.finding(
                ctx, F004, call,
                f"{fdef.name}(): drain() result discarded — the fenced "
                f"replica's in-flight requests are dropped on the floor; "
                f"requeue_front() them (or retire them with the queue)")
            if f is not None:
                out.append(f)
        if not drain_assigns:
            return out
        cfg = df.cfg(fdef, ctx.path)
        gen: Dict[int, Set[Tuple[str, int]]] = {}
        tracked: Set[str] = set()
        for var, assign in drain_assigns:
            idx = cfg.node_of(assign)
            if idx is not None:
                gen.setdefault(idx, set()).add((var, idx))
                tracked.add(var)
        if not gen:
            return out
        kills: Dict[int, Tuple[Set[str], bool]] = {}
        for n in cfg.nodes:
            if n.stmt is None:
                continue
            names, kill_all = self._drain_discharges(n.stmt, tracked)
            if names or kill_all:
                kills[n.idx] = (names, kill_all)

        def transfer(idx, inset):
            cur = inset
            ks = kills.get(idx)
            if ks:
                names, kill_all = ks
                cur = frozenset(
                    f for f in cur
                    if not kill_all and f[0] not in names)
            g = gen.get(idx)
            if g:
                cur = frozenset(f for f in cur
                                if f[0] not in {v for v, _ in g})
                cur = cur | frozenset(g)
            return cur

        sets = dataflow.solve(cfg, direction="forward", transfer=transfer,
                              kinds=dataflow.NO_PANIC)
        leaked = sets[dataflow.CFG.EXIT][0]
        for var, node_idx in sorted(leaked, key=lambda f: (f[1], f[0])):
            avoid = {i for i, (names, kill_all) in kills.items()
                     if kill_all or var in names}
            path = cfg.find_path(node_idx, dataflow.CFG.EXIT, avoid=avoid,
                                 kinds=dataflow.NO_PANIC)
            desc = cfg.describe_path(path) if path else "<path unavailable>"
            f = self.finding(
                ctx, F004, cfg.nodes[node_idx].stmt,
                f"{fdef.name}(): drained request list '{var}' can reach "
                f"function exit without re-admission on the path [{desc}] "
                f"— requeue_front() it (or close the queue) before every "
                f"exit")
            if f is not None:
                out.append(f)
        return out

    # ------------------------------------------------------------------ F005
    def _span_discharges(self, stmt, tracked: Set[str]) -> Set[str]:
        """Names discharged by this statement, for the span obligation.

        A span bound by ``sp = tracer.begin_span(...)`` is discharged
        by: appearing in the arguments of an ``end_span(...)`` call;
        being returned/yielded (the caller owns the close now — the
        ``span()`` context manager's yield is exactly this); or being
        stored into an attribute/subscript (escapes to an object that
        outlives the frame and closes it later)."""
        names: Set[str] = set()
        for sub in walk_stop_at_defs(stmt):
            if isinstance(sub, ast.Call) and _attr_leaf(sub) in _SPAN_CLOSE:
                for arg in list(sub.args) + [k.value for k in sub.keywords]:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and sub.value is not None:
                for n in ast.walk(sub.value):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(sub, ast.Assign):
                stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in sub.targets)
                if stores:
                    for n in ast.walk(sub.value):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        return names & tracked if tracked else set()

    def _check_span_close(self, ctx, df, fdef) -> Iterable[Finding]:
        span_assigns: List[Tuple[str, ast.Assign]] = []
        discarded: List[ast.Call] = []
        for sub in walk_stop_at_defs(fdef):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _attr_leaf(sub.value) in _SPAN_OPEN:
                span_assigns.append((sub.targets[0].id, sub))
            elif isinstance(sub, ast.Expr) and isinstance(sub.value,
                                                          ast.Call) \
                    and _attr_leaf(sub.value) in _SPAN_OPEN:
                discarded.append(sub.value)
        out = []
        for call in discarded:
            f = self.finding(
                ctx, F005, call,
                f"{fdef.name}(): begin_span(...) result discarded — the "
                f"span can never be end_span()d, so it is never committed "
                f"to the trace store; bind it, or use record_span() for a "
                f"one-shot span")
            if f is not None:
                out.append(f)
        if not span_assigns:
            return out
        cfg = df.cfg(fdef, ctx.path)
        gen: Dict[int, Set[Tuple[str, int]]] = {}
        tracked: Set[str] = set()
        for var, assign in span_assigns:
            idx = cfg.node_of(assign)
            if idx is not None:
                gen.setdefault(idx, set()).add((var, idx))
                tracked.add(var)
        if not gen:
            return out
        kills: Dict[int, Set[str]] = {}
        for n in cfg.nodes:
            if n.stmt is None:
                continue
            names = self._span_discharges(n.stmt, tracked)
            if names:
                kills[n.idx] = names

        def transfer(idx, inset):
            cur = inset
            ks = kills.get(idx)
            if ks:
                cur = frozenset(f for f in cur if f[0] not in ks)
            g = gen.get(idx)
            if g:
                cur = frozenset(f for f in cur
                                if f[0] not in {v for v, _ in g})
                cur = cur | frozenset(g)
            return cur

        # ALL_KINDS, like F001: a span must close on exception paths
        # too — end_span() belongs in a finally (or use `with span()`)
        sets = dataflow.solve(cfg, direction="forward", transfer=transfer,
                              kinds=dataflow.ALL_KINDS)
        leaked = sets[dataflow.CFG.EXIT][0]
        for var, node_idx in sorted(leaked, key=lambda f: (f[1], f[0])):
            avoid = {i for i, names in kills.items() if var in names}
            path = cfg.find_path(node_idx, dataflow.CFG.EXIT, avoid=avoid)
            desc = cfg.describe_path(path) if path else "<path unavailable>"
            f = self.finding(
                ctx, F005, cfg.nodes[node_idx].stmt,
                f"{fdef.name}(): span '{var}' opened here can reach "
                f"function exit without end_span() on the path [{desc}] — "
                f"close it in a finally, or open it with the span() "
                f"context manager")
            if f is not None:
                out.append(f)
        return out

    # ------------------------------------------------------------------ F006
    def _standby_discharges(self, stmt, tracked: Set[str]) -> Set[str]:
        """Names discharged by this statement, for the standby either/or.

        A standby bound by ``sb = rset.acquire_standby(...)`` is
        discharged by: a promote/swap_in or stop/abandon call with the
        name as RECEIVER (``sb.promote(reason)`` — the idiomatic shape)
        or as an argument (``rset.swap_in(sb)``); being returned/yielded
        (the caller owns the either/or now); or being stored into an
        attribute/subscript (an object that outlives the frame owns
        it)."""
        names: Set[str] = set()
        for sub in walk_stop_at_defs(stmt):
            if isinstance(sub, ast.Call) \
                    and _attr_leaf(sub) in (_PROMOTES | _TEARDOWNS):
                if isinstance(sub.func, ast.Attribute) \
                        and isinstance(sub.func.value, ast.Name):
                    names.add(sub.func.value.id)
                for arg in list(sub.args) + [k.value for k in sub.keywords]:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
            elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and sub.value is not None:
                for n in ast.walk(sub.value):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
            elif isinstance(sub, ast.Assign):
                stores = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in sub.targets)
                if stores:
                    for n in ast.walk(sub.value):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        return names & tracked if tracked else set()

    def _check_standby_lifecycle(self, ctx, df, fdef) -> Iterable[Finding]:
        standby_assigns: List[Tuple[str, ast.Assign]] = []
        discarded: List[ast.Call] = []
        for sub in walk_stop_at_defs(fdef):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name) \
                    and isinstance(sub.value, ast.Call) \
                    and _attr_leaf(sub.value) == _STANDBY_MAKER:
                standby_assigns.append((sub.targets[0].id, sub))
            elif isinstance(sub, ast.Expr) and isinstance(sub.value,
                                                          ast.Call) \
                    and _attr_leaf(sub.value) == _STANDBY_MAKER:
                discarded.append(sub.value)
        out = []
        for call in discarded:
            f = self.finding(
                ctx, F006, call,
                f"{fdef.name}(): acquire_standby(...) result discarded — "
                f"the standby (an engine + KV pool outside the set) can "
                f"never be promoted or torn down; bind it and promote() "
                f"or abandon() it")
            if f is not None:
                out.append(f)
        if not standby_assigns:
            return out
        cfg = df.cfg(fdef, ctx.path)
        gen: Dict[int, Set[Tuple[str, int]]] = {}
        tracked: Set[str] = set()
        for var, assign in standby_assigns:
            idx = cfg.node_of(assign)
            if idx is not None:
                gen.setdefault(idx, set()).add((var, idx))
                tracked.add(var)
        if not gen:
            return out
        kills: Dict[int, Set[str]] = {}
        for n in cfg.nodes:
            if n.stmt is None:
                continue
            names = self._standby_discharges(n.stmt, tracked)
            if names:
                kills[n.idx] = names

        def transfer(idx, inset):
            cur = inset
            ks = kills.get(idx)
            if ks:
                cur = frozenset(f for f in cur if f[0] not in ks)
            g = gen.get(idx)
            if g:
                cur = frozenset(f for f in cur
                                if f[0] not in {v for v, _ in g})
                cur = cur | frozenset(g)
            return cur

        sets = dataflow.solve(cfg, direction="forward", transfer=transfer,
                              kinds=dataflow.NO_PANIC)
        leaked = sets[dataflow.CFG.EXIT][0]
        for var, node_idx in sorted(leaked, key=lambda f: (f[1], f[0])):
            avoid = {i for i, names in kills.items() if var in names}
            path = cfg.find_path(node_idx, dataflow.CFG.EXIT, avoid=avoid,
                                 kinds=dataflow.NO_PANIC)
            desc = cfg.describe_path(path) if path else "<path unavailable>"
            f = self.finding(
                ctx, F006, cfg.nodes[node_idx].stmt,
                f"{fdef.name}(): standby replica '{var}' acquired here "
                f"can reach function exit neither promoted nor torn down "
                f"on the path [{desc}] — promote() it into the set or "
                f"abandon() it on every exit (the boot-budget timeout "
                f"and exception branches included)")
            if f is not None:
                out.append(f)
        return out

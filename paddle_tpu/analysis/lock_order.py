"""Runtime lock-order witness: potential-deadlock detection for framework locks.

The static half of this package proves discipline *within* a function;
cross-thread lock ORDER is a runtime property. This module wraps framework
locks (under ``FLAGS_lock_order_check``) so every acquisition while other
locks are held records a directed edge ``held -> acquired`` into a global
graph. A cycle in that graph is a potential deadlock — the ABBA inversion
— even if the schedule never actually interleaved badly during the run.
That "witness" approach is how TSan's deadlock detector and the kernel's
lockdep work: one good run proves the ordering invariant, no unlucky
timing required.

Standalone-importable by design: NO paddle_tpu imports at module level, so
``tests/conftest.py`` can load this file by path and install the witness
*before* ``paddle_tpu`` is imported — module-level framework locks are
then created through the patched constructors and get instrumented too.
``install()`` only instruments locks whose creating frame lives inside
paddle_tpu; jax/numpy/stdlib internals keep raw locks (zero overhead where
we don't own the code).

Also here: ``thread_leak_report`` — the post-test check that framework
threads didn't leak (non-daemon threads outliving the suite hang the
interpreter at exit; that contract is why C001 wants ``daemon=`` explicit).
"""
from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderGraph", "WitnessLock", "get_graph", "install", "uninstall",
    "installed", "wrap", "thread_leak_report",
]


class LockOrderGraph:
    """Directed graph of observed lock-acquisition edges, with cycle
    (potential-deadlock) detection.

    Nodes are lock names (creation site ``path:line`` for auto-wrapped
    locks). ``record`` is called with the innermost held lock and the one
    being acquired; first-seen context is kept per edge for the report."""

    def __init__(self):
        self._lock = threading.Lock()
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------
    def _held(self) -> List[str]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def on_acquired(self, name: str):
        held = self._held()
        for h in held:
            if h != name:
                self._record(h, name)
        held.append(name)

    def on_released(self, name: str):
        held = self._held()
        # remove the LAST occurrence: release order may not mirror acquire
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break

    def _record(self, a: str, b: str):
        key = (a, b)
        if key in self._edges:
            with self._lock:
                self._edges[key]["count"] += 1
            return
        stack = "".join(traceback.format_stack(sys._getframe(3), limit=4))
        with self._lock:
            self._edges.setdefault(key, {
                "count": 0,
                "thread": threading.current_thread().name,
                "stack": stack,
            })["count"] += 1

    # -- analysis -------------------------------------------------------------
    def edges(self) -> Dict[Tuple[str, str], dict]:
        with self._lock:
            return dict(self._edges)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles via iterative DFS with a colour map; each
        cycle reported once, rotated to start at its smallest node."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges():
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        seen_cycles = set()
        out: List[List[str]] = []
        for start in sorted(adj):
            stack = [(start, iter(sorted(adj[start])))]
            path = [start]
            on_path = {start}
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if nxt in on_path:
                        i = path.index(nxt)
                        cyc = path[i:]
                        k = min(range(len(cyc)), key=lambda j: cyc[j])
                        canon = tuple(cyc[k:] + cyc[:k])
                        if canon not in seen_cycles:
                            seen_cycles.add(canon)
                            out.append(list(canon))
                    elif nxt > start or nxt == start:
                        # only explore nodes >= start: each cycle found from
                        # its smallest member, avoiding duplicates
                        if nxt >= start:
                            stack.append((nxt, iter(sorted(adj[nxt]))))
                            path.append(nxt)
                            on_path.add(nxt)
                            advanced = True
                            break
                if not advanced:
                    stack.pop()
                    on_path.discard(path.pop())
        return out

    def report(self) -> dict:
        edges = self.edges()
        cycles = self.cycles()
        cyc_nodes = {n for c in cycles for n in c}
        detail = []
        for c in cycles:
            pairs = list(zip(c, c[1:] + c[:1]))
            detail.append({
                "nodes": c,
                "edges": [{
                    "from": a, "to": b,
                    **{k: v for k, v in edges.get((a, b), {}).items()}
                } for a, b in pairs],
            })
        return {
            "locks": sorted({n for e in edges for n in e}),
            "edge_count": len(edges),
            "cycles": detail,
            "cycle_lock_names": sorted(cyc_nodes),
        }

    def clear(self):
        with self._lock:
            self._edges.clear()


_global_graph = LockOrderGraph()


def get_graph() -> LockOrderGraph:
    return _global_graph


class WitnessLock:
    """Wraps a real Lock/RLock; reports acquisition edges to a graph.

    Duck-types the full lock protocol (works as the lock of a
    ``threading.Condition``: unknown attributes delegate to the real
    lock, so RLock's _is_owned/_release_save remain visible)."""

    _created = 0      # class-wide count, for the sanitizer's summary line

    def __init__(self, real, name: str,
                 graph: Optional[LockOrderGraph] = None,
                 reentrant: bool = False):
        self._real = real
        self.name = name
        self._graph = graph or _global_graph
        self._reentrant = reentrant
        WitnessLock._created += 1

    def acquire(self, blocking=True, timeout=-1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._graph.on_acquired(self.name)
        return got

    def release(self):
        self._real.release()
        self._graph.on_released(self.name)

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()  # lint-ok: C002 context-manager protocol: __exit__ is the release
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, attr):
        return getattr(self._real, attr)

    def __repr__(self):
        return f"<WitnessLock {self.name} wrapping {self._real!r}>"


def wrap(lock, name: str, graph: Optional[LockOrderGraph] = None):
    """Explicitly instrument an existing lock object."""
    if isinstance(lock, WitnessLock):
        return lock
    return WitnessLock(lock, name, graph)


# ---------------------------------------------------------------------------
# constructor patching: threading.Lock/RLock become factories that wrap
# locks created from paddle_tpu code (creation-site named path:line).
# ---------------------------------------------------------------------------

_orig: dict = {}


def _should_instrument(frame) -> Optional[str]:
    fn = frame.f_code.co_filename.replace(os.sep, "/")
    if "paddle_tpu" not in fn:
        return None
    if fn.endswith("analysis/lock_order.py"):
        return None  # our own graph lock must stay raw (no recursion)
    tail = fn.split("paddle_tpu/")[-1]
    return f"paddle_tpu/{tail}:{frame.f_lineno}"


def install(graph: Optional[LockOrderGraph] = None):
    """Patch threading.Lock/RLock so locks created by paddle_tpu code are
    witnesses. Idempotent; call ``uninstall()`` to restore."""
    if _orig:
        return
    g = graph or _global_graph
    real_lock, real_rlock = threading.Lock, threading.RLock
    _orig["Lock"], _orig["RLock"] = real_lock, real_rlock

    def lock_factory():
        real = real_lock()
        name = _should_instrument(sys._getframe(1))
        return WitnessLock(real, name, g) if name else real

    def rlock_factory():
        real = real_rlock()
        name = _should_instrument(sys._getframe(1))
        return WitnessLock(real, name, g, reentrant=True) if name else real

    threading.Lock = lock_factory
    threading.RLock = rlock_factory


def uninstall():
    if _orig:
        threading.Lock = _orig.pop("Lock")
        threading.RLock = _orig.pop("RLock")


def installed() -> bool:
    return bool(_orig)


def witness_count() -> int:
    """How many locks have been wrapped (lifetime, all graphs)."""
    return WitnessLock._created


# ---------------------------------------------------------------------------
# thread-leak check (post-test): non-daemon threads outliving the suite
# ---------------------------------------------------------------------------

def thread_leak_report(baseline_names: Optional[Set[str]] = None) -> List[dict]:
    """Alive non-daemon threads beyond main (and beyond ``baseline_names``
    captured at session start). These hang interpreter shutdown — every
    framework background thread declares daemon=True for exactly this
    reason (rule C001)."""
    baseline_names = baseline_names or set()
    leaks = []
    for t in threading.enumerate():
        if t is threading.main_thread() or t.daemon or not t.is_alive():
            continue
        if t.name in baseline_names:
            continue
        leaks.append({"name": t.name, "ident": t.ident,
                      "daemon": t.daemon})
    return leaks

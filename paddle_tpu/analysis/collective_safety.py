"""Collective-safety rules: the SPMD invariants of distributed/.

The failure mode these guard is the worst one distributed training has:
a collective issued on some ranks but not others, or outside the guarded
execution path, hangs every rank forever with no error. PR 4 routed every
eager collective through ``execute_collective`` (timeout + retry + chaos
injection); these rules keep that funnel — and the no-rank-conditional-
collective shape — machine-checked.

X001  raw ``jax.lax`` collective primitives (psum, all_gather, ppermute,
      all_to_all, ...) stay inside ``paddle_tpu/distributed/`` — other
      layers use the public ``distributed.collective`` API so bytes
      accounting, tracing, and guards apply.
X002  (a) ``execute_collective`` is called only by the collective layer
      and the robustness runtime that owns it; (b) inside
      ``distributed/collective.py``, every eager thunk (a nested function
      named ``_eager*``) is submitted through ``_guarded(...)`` — the
      shim that rides ``execute_collective``.
X003  an ``if`` whose test mentions rank must not issue a collective in
      only one branch — the classic ABBA-free but still deadlocking SPMD
      shape (some ranks enter the collective, the rest never arrive).
X004  X003's interprocedural extension (ISSUE 11): the same rank-
      conditional shape where the collective hides behind a call — the
      branch calls a function that (followed through the project call
      graph on CONFIDENT edges only) transitively issues one. Generic
      leaves the direct set tolerates (``send``/``recv``/``reduce``/
      ``scatter``) are excluded transitively: one call away they are
      usually sockets and functools, not SPMD.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .engine import Checker, FileContext, Finding, register_rule

X001 = register_rule(
    "X001",
    "raw jax.lax collective primitives only inside paddle_tpu/distributed/",
    "bypassing distributed.collective skips bytes counters, flight-recorder "
    "lane records, and the PR-4 timeout/retry guards")
X002 = register_rule(
    "X002",
    "every eager collective rides execute_collective (via _guarded)",
    "an unguarded eager collective hangs forever on rank loss instead of "
    "raising CollectiveTimeoutError and escalating to the HangDetector")
X003 = register_rule(
    "X003",
    "no rank-conditional branch that issues a collective in only one arm",
    "if some ranks enter a collective and others never arrive, every rank "
    "blocks until the timeout — the classic SPMD deadlock shape")
X004 = register_rule(
    "X004",
    "no rank-conditional branch that TRANSITIVELY calls into a "
    "collective-issuing function in only one arm",
    "X003 catches the collective written in the branch; the same deadlock "
    "hides one call away — a rank-gated helper whose callee (followed "
    "through the project call graph) issues the collective for it")

# jax.lax primitives that are cross-replica communication
_LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle",
}

# public collective-layer entry points (distributed/collective.py et al.)
_API_COLLECTIVES = {
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "reduce",
    "scatter", "alltoall", "barrier", "send", "recv", "sendrecv",
} | _LAX_COLLECTIVES

_RANK_MARKERS = {"rank", "local_rank", "src_rank", "dst_rank", "rank_id",
                 "get_rank", "get_rank_in", "get_group_rank", "local_rank_id"}

# X004's transitive classification excludes the generic leaves of the
# direct set ("send", "recv", "reduce", "scatter"): one call away, a
# socket.send or functools.reduce inside a resolved helper would flood
# the rule with false positives the direct X003 form never sees
_X004_COLLECTIVES = _LAX_COLLECTIVES | {
    "all_reduce", "all_gather", "reduce_scatter", "alltoall", "barrier",
    "broadcast", "sendrecv",
}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_leaf(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    return d.rsplit(".", 1)[-1] if d else None


def _is_lax_collective(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    return (len(parts) >= 2 and parts[-2] == "lax"
            and parts[-1] in _LAX_COLLECTIVES)


class CollectiveSafetyChecker(Checker):
    name = "collective_safety"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        out: List[Optional[Finding]] = []
        out.extend(self._check_raw_primitives(ctx))
        out.extend(self._check_execute_collective_funnel(ctx))
        if ctx.path.endswith("distributed/collective.py"):
            out.extend(self._check_eager_thunks_guarded(ctx))
        out.extend(self._check_rank_conditional(ctx, shared))
        return [f for f in out if f is not None]

    # -- X001 ---------------------------------------------------------------
    def _check_raw_primitives(self, ctx: FileContext):
        if "/distributed/" in ctx.path or ctx.path.endswith("conftest.py"):
            return
        for node in ctx.walk():
            if isinstance(node, ast.Call) and _is_lax_collective(node):
                yield self.finding(
                    ctx, X001, node,
                    f"raw jax.lax.{_call_leaf(node)} outside "
                    "paddle_tpu/distributed/ — use distributed.collective")

    # -- X002a --------------------------------------------------------------
    def _check_execute_collective_funnel(self, ctx: FileContext):
        if ("distributed/collective.py" in ctx.path
                or "/robustness/" in ctx.path):
            return
        for node in ctx.walk():
            name = None
            if isinstance(node, ast.Call):
                leaf = _call_leaf(node)
                if leaf == "execute_collective":
                    name = leaf
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "execute_collective":
                        name = alias.name
            if name:
                yield self.finding(
                    ctx, X002, node,
                    "execute_collective used outside the collective layer — "
                    "call distributed.collective's public API instead")

    # -- X002b --------------------------------------------------------------
    def _check_eager_thunks_guarded(self, ctx: FileContext):
        for outer in ctx.walk():
            if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            thunks = [n for n in outer.body
                      if isinstance(n, ast.FunctionDef)
                      and n.name.startswith("_eager")]
            if not thunks:
                continue
            guarded_args = set()
            for node in ast.walk(outer):
                if (isinstance(node, ast.Call)
                        and _call_leaf(node) in ("_guarded",
                                                 "execute_collective")):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            guarded_args.add(a.id)
            for t in thunks:
                if t.name not in guarded_args:
                    yield self.finding(
                        ctx, X002, t,
                        f"eager thunk {t.name}() in {outer.name}() is never "
                        "passed to _guarded()/execute_collective — timeouts "
                        "and chaos injection will not apply")

    # -- X003 / X004 --------------------------------------------------------
    def _check_rank_conditional(self, ctx: FileContext, shared=None):
        index = (shared or {}).get("project_index")
        for node in ctx.walk():
            if not isinstance(node, ast.If):
                continue
            if not self._mentions_rank(node.test):
                continue
            body_coll = self._first_collective(node.body)
            else_coll = self._first_collective(node.orelse)
            if (body_coll is None) != (else_coll is None):
                coll = body_coll if body_coll is not None else else_coll
                yield self.finding(
                    ctx, X003, node,
                    f"rank-conditional branch issues collective "
                    f"'{coll}' in only one arm — SPMD deadlock shape")
                continue
            if body_coll is not None or index is None:
                continue  # both arms communicate directly: symmetric
            # X004: neither arm is direct — follow the call graph
            body_reach = self._transitive_collective(ctx, node.body, index)
            else_reach = self._transitive_collective(ctx, node.orelse, index)
            if (body_reach is None) == (else_reach is None):
                continue
            tgt, via = body_reach if body_reach is not None else else_reach
            yield self.finding(
                ctx, X004, node,
                f"rank-conditional branch calls {tgt}() which transitively "
                f"issues collective '{via}' in only one arm — SPMD "
                "deadlock one call away")

    def _transitive_collective(self, ctx: FileContext, body, index):
        """(called_name, collective_leaf) when a call in ``body`` reaches a
        collective-issuing function through CONFIDENT call-graph edges."""
        enclosing = self._enclosing_function(ctx, body, index)
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                d = _dotted(sub.func)
                if d is None or enclosing is None:
                    continue
                for q in index.resolve(d, enclosing, fallback=False):
                    via = self._issues_collective(index, q)
                    if via is not None:
                        return (d.rsplit(".", 1)[-1], via)
        return None

    def _enclosing_function(self, ctx: FileContext, body, index):
        """The FunctionNode whose body (transitively) contains ``body`` —
        resolution context for calls inside the branch."""
        target = body[0] if body else None
        if target is None:
            return None
        best = None
        for node in ctx.walk():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if sub is target:
                        best = node  # innermost wins: keep walking
        return index.node_for(best) if best is not None else None

    @classmethod
    def _issues_collective(cls, index, qualname) -> Optional[str]:
        """Leaf name of a collective issued by ``qualname`` or anything it
        confidently reaches, else None (memoized on the index)."""
        cache = index.__dict__.setdefault("_x004_issues", {})
        if qualname in cache:
            return cache[qualname]
        cache[qualname] = None    # cycle guard
        fn = index.functions.get(qualname)
        if fn is None:
            return None
        direct = cls._direct_collective(fn)
        if direct is not None:
            cache[qualname] = direct
            return direct
        for q in index.reachable(qualname, fallback=False):
            node = index.functions.get(q)
            if node is None:
                continue
            direct = cls._direct_collective(node)
            if direct is not None:
                cache[qualname] = direct
                return direct
        return None

    @staticmethod
    def _direct_collective(fn) -> Optional[str]:
        for dotted, call in fn.calls:
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _X004_COLLECTIVES or _is_lax_collective(call):
                return leaf
        return None

    @staticmethod
    def _mentions_rank(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in _RANK_MARKERS:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in _RANK_MARKERS:
                return True
        return False

    @staticmethod
    def _first_collective(body) -> Optional[str]:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    leaf = _call_leaf(sub)
                    if leaf in _API_COLLECTIVES:
                        return leaf
        return None

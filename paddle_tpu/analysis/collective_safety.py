"""Collective-safety rules: the SPMD invariants of distributed/.

The failure mode these guard is the worst one distributed training has:
a collective issued on some ranks but not others, or outside the guarded
execution path, hangs every rank forever with no error. PR 4 routed every
eager collective through ``execute_collective`` (timeout + retry + chaos
injection); these rules keep that funnel — and the no-rank-conditional-
collective shape — machine-checked.

X001  raw ``jax.lax`` collective primitives (psum, all_gather, ppermute,
      all_to_all, ...) stay inside ``paddle_tpu/distributed/`` — other
      layers use the public ``distributed.collective`` API so bytes
      accounting, tracing, and guards apply.
X002  (a) ``execute_collective`` is called only by the collective layer
      and the robustness runtime that owns it; (b) inside
      ``distributed/collective.py``, every eager thunk (a nested function
      named ``_eager*``) is submitted through ``_guarded(...)`` — the
      shim that rides ``execute_collective``.
X003  an ``if`` whose test mentions rank must not issue a collective in
      only one branch — the classic ABBA-free but still deadlocking SPMD
      shape (some ranks enter the collective, the rest never arrive).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from .engine import Checker, FileContext, Finding, register_rule

X001 = register_rule(
    "X001",
    "raw jax.lax collective primitives only inside paddle_tpu/distributed/",
    "bypassing distributed.collective skips bytes counters, flight-recorder "
    "lane records, and the PR-4 timeout/retry guards")
X002 = register_rule(
    "X002",
    "every eager collective rides execute_collective (via _guarded)",
    "an unguarded eager collective hangs forever on rank loss instead of "
    "raising CollectiveTimeoutError and escalating to the HangDetector")
X003 = register_rule(
    "X003",
    "no rank-conditional branch that issues a collective in only one arm",
    "if some ranks enter a collective and others never arrive, every rank "
    "blocks until the timeout — the classic SPMD deadlock shape")

# jax.lax primitives that are cross-replica communication
_LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle",
}

# public collective-layer entry points (distributed/collective.py et al.)
_API_COLLECTIVES = {
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "reduce",
    "scatter", "alltoall", "barrier", "send", "recv", "sendrecv",
} | _LAX_COLLECTIVES

_RANK_MARKERS = {"rank", "local_rank", "src_rank", "dst_rank", "rank_id",
                 "get_rank", "get_rank_in", "get_group_rank", "local_rank_id"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_leaf(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    return d.rsplit(".", 1)[-1] if d else None


def _is_lax_collective(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d is None:
        return False
    parts = d.split(".")
    return (len(parts) >= 2 and parts[-2] == "lax"
            and parts[-1] in _LAX_COLLECTIVES)


class CollectiveSafetyChecker(Checker):
    name = "collective_safety"

    def check(self, ctx: FileContext, shared: dict) -> Iterable[Finding]:
        out: List[Optional[Finding]] = []
        out.extend(self._check_raw_primitives(ctx))
        out.extend(self._check_execute_collective_funnel(ctx))
        if ctx.path.endswith("distributed/collective.py"):
            out.extend(self._check_eager_thunks_guarded(ctx))
        out.extend(self._check_rank_conditional(ctx))
        return [f for f in out if f is not None]

    # -- X001 ---------------------------------------------------------------
    def _check_raw_primitives(self, ctx: FileContext):
        if "/distributed/" in ctx.path or ctx.path.endswith("conftest.py"):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_lax_collective(node):
                yield self.finding(
                    ctx, X001, node,
                    f"raw jax.lax.{_call_leaf(node)} outside "
                    "paddle_tpu/distributed/ — use distributed.collective")

    # -- X002a --------------------------------------------------------------
    def _check_execute_collective_funnel(self, ctx: FileContext):
        if ("distributed/collective.py" in ctx.path
                or "/robustness/" in ctx.path):
            return
        for node in ast.walk(ctx.tree):
            name = None
            if isinstance(node, ast.Call):
                leaf = _call_leaf(node)
                if leaf == "execute_collective":
                    name = leaf
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "execute_collective":
                        name = alias.name
            if name:
                yield self.finding(
                    ctx, X002, node,
                    "execute_collective used outside the collective layer — "
                    "call distributed.collective's public API instead")

    # -- X002b --------------------------------------------------------------
    def _check_eager_thunks_guarded(self, ctx: FileContext):
        for outer in ast.walk(ctx.tree):
            if not isinstance(outer, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            thunks = [n for n in outer.body
                      if isinstance(n, ast.FunctionDef)
                      and n.name.startswith("_eager")]
            if not thunks:
                continue
            guarded_args = set()
            for node in ast.walk(outer):
                if (isinstance(node, ast.Call)
                        and _call_leaf(node) in ("_guarded",
                                                 "execute_collective")):
                    for a in node.args:
                        if isinstance(a, ast.Name):
                            guarded_args.add(a.id)
            for t in thunks:
                if t.name not in guarded_args:
                    yield self.finding(
                        ctx, X002, t,
                        f"eager thunk {t.name}() in {outer.name}() is never "
                        "passed to _guarded()/execute_collective — timeouts "
                        "and chaos injection will not apply")

    # -- X003 ---------------------------------------------------------------
    def _check_rank_conditional(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if not self._mentions_rank(node.test):
                continue
            body_coll = self._first_collective(node.body)
            else_coll = self._first_collective(node.orelse)
            if (body_coll is None) == (else_coll is None):
                continue  # both arms or neither arm communicate: symmetric
            coll = body_coll if body_coll is not None else else_coll
            yield self.finding(
                ctx, X003, node,
                f"rank-conditional branch issues collective "
                f"'{coll}' in only one arm — SPMD deadlock shape")

    @staticmethod
    def _mentions_rank(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in _RANK_MARKERS:
                return True
            if isinstance(sub, ast.Attribute) and sub.attr in _RANK_MARKERS:
                return True
        return False

    @staticmethod
    def _first_collective(body) -> Optional[str]:
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    leaf = _call_leaf(sub)
                    if leaf in _API_COLLECTIVES:
                        return leaf
        return None

"""Project-wide symbol table + call graph for interprocedural rules.

PR 7's checkers judged one AST at a time; the two worst latent bugs of
PRs 8-10 (the TrainStep donation-alias ordering, the replicated-residual
divergence) were CROSS-boundary: visible only by following a call from
one function into another. This module gives every checker that view —
pure stdlib, built once per analysis run over all files, cheap enough to
stay inside the tier-1 wall-time budget.

Design:

- ``FunctionNode`` — one def (module-level fn, method, or nested fn),
  carrying its call sites as *dotted name strings* plus the raw
  ``ast.Call`` nodes, so rule modules apply their own classification
  (collective-issuing, host-impure, ...) without re-walking files.
- ``ProjectIndex`` — the symbol table: functions by qualname
  (``path::Qual.name``), module import tables, lexical-scope visibility,
  and the resolver that turns a dotted call string at one site into
  callee qualnames.
- Edges come in two confidences. *Confident*: same-scope names,
  ``self.``/``cls.`` methods of the enclosing class, and names resolved
  through the module's import table (absolute and relative imports).
  *Fallback*: an attribute call whose leaf name matches exactly ONE
  function in the whole project. Rules that must not false-positive
  (X004, T003) traverse confident edges only; the generic
  ``reachable()`` query takes either.
- A nested def gets an implicit parent→child edge: a closure is part of
  its parent's behavior for reachability purposes (it is either called
  there or escapes from there).

Reachability is memoized per (root, confidence); the whole index over
the ~340-file tree builds in well under a second.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["FunctionNode", "ProjectIndex", "build_index", "dotted_name",
           "module_of"]

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def module_of(path: str) -> str:
    """Repo-relative posix path -> dotted module name
    (``paddle_tpu/distributed/collective.py`` ->
    ``paddle_tpu.distributed.collective``)."""
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def walk_stop_at_defs(root: ast.AST):
    """Yield every node under ``root`` without descending into nested
    function definitions (the root itself may be a def)."""
    stack = [root]
    first = True
    while stack:
        node = stack.pop()
        if not first and isinstance(node, _DEFS):
            continue
        first = False
        yield node
        stack.extend(ast.iter_child_nodes(node))


class FunctionNode:
    __slots__ = (
        "qualname", "path", "module", "name", "qual", "class_name", "node",
        "calls", "children", "lineno", "visible", "has_in_trace_guard",
    )

    def __init__(self, qualname, path, module, name, qual, class_name, node,
                 visible):
        self.qualname = qualname
        self.path = path
        self.module = module
        self.name = name              # bare name ("materialize")
        self.qual = qual              # dotted qual inside the module
        self.class_name = class_name  # immediately-enclosing class, if any
        self.node = node
        self.lineno = getattr(node, "lineno", 0)
        self.calls: List[Tuple[str, ast.Call]] = []   # own body, excl. children
        self.children: List[str] = []                 # nested-def qualnames
        self.visible: Dict[str, str] = visible        # lexical name -> qualname
        # a function that explicitly branches on _in_trace()/in-trace state
        # handles the eager and traced worlds itself (the dual-path contract
        # of the collective layer) — interprocedural purity rules stop here
        self.has_in_trace_guard = False

    def __repr__(self):
        return f"<FunctionNode {self.qualname}>"


class _FileIndexer:
    """One pass over one module: defs, imports, per-function call sites."""

    def __init__(self, index: "ProjectIndex", path: str, tree: ast.Module):
        self.index = index
        self.path = path
        self.module = module_of(path)
        self.tree = tree

    def run(self):
        idx = self.index
        idx.modules.add(self.module)
        imports = idx.imports.setdefault(self.module, {})
        self._collect_imports(self.tree, imports)
        self._scan_scope(self.tree.body, qual_prefix="", class_name=None,
                         visible={})

    # -- imports -------------------------------------------------------------
    def _collect_imports(self, tree, imports: Dict[str, str]):
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        imports[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        imports[head] = head
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    imports[a.asname or a.name] = f"{base}.{a.name}"

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative import: climb from this module's package
        pkg = self.module.split(".")
        if not self.path.endswith("__init__.py"):
            pkg = pkg[:-1]          # a module file's package is its parent
        drop = node.level - 1
        if drop > len(pkg):
            return None
        base = pkg[: len(pkg) - drop] if drop else list(pkg)
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base) if base else None

    # -- defs ----------------------------------------------------------------
    def _scan_scope(self, body, qual_prefix, class_name, visible):
        """Class bodies / module body: register defs, descend into classes
        and compound statements. (Calls at class/module level are not
        attributed to any function — there is none.)"""
        local = dict(visible)
        defs = []
        for stmt in self._stmts(body):
            if isinstance(stmt, _DEFS):
                defs.append(stmt)
                local[stmt.name] = f"{self.path}::{qual_prefix}{stmt.name}"
        for stmt in self._stmts(body):
            if isinstance(stmt, _DEFS):
                self._add_function(stmt, f"{qual_prefix}{stmt.name}",
                                   class_name, None, local)
            elif isinstance(stmt, ast.ClassDef):
                self._scan_scope(stmt.body, f"{qual_prefix}{stmt.name}.",
                                 stmt.name, local)

    @staticmethod
    def _stmts(body):
        """Statements of a scope, looking through If/Try/With/For/While
        wrappers (a def under ``if TYPE_CHECKING:`` is still a scope def)."""
        out = []
        stack = list(body)
        while stack:
            stmt = stack.pop(0)
            out.append(stmt)
            if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                 ast.While)):
                for attr in ("body", "orelse", "finalbody"):
                    stack.extend(getattr(stmt, attr, []))
                for h in getattr(stmt, "handlers", []):
                    stack.extend(h.body)
        return out

    def _add_function(self, node, qual, class_name, parent, visible):
        idx = self.index
        qualname = f"{self.path}::{qual}"
        fn = FunctionNode(qualname, self.path, self.module, node.name, qual,
                          class_name, node, visible)
        idx.functions[qualname] = fn
        idx.by_node[id(node)] = qualname
        idx.by_name.setdefault(node.name, []).append(qualname)
        if class_name is None and "." not in qual:
            idx.module_level.setdefault(self.module, {})[node.name] = qualname
        if class_name is not None:
            idx.methods.setdefault(self.module, {}).setdefault(
                class_name, {})[node.name] = qualname
        if parent is not None:
            parent.children.append(qualname)

        # nested defs anywhere inside this function (stopping at their
        # bodies): registered first so siblings see each other
        nested = []
        local = dict(visible)

        def collect(n):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, _DEFS):
                    nested.append(child)
                    local[child.name] = f"{self.path}::{qual}.{child.name}"
                else:
                    collect(child)
        collect(node)

        # own call sites: everything under this def except nested def bodies
        for sub in walk_stop_at_defs(node):
            if isinstance(sub, ast.Call):
                d = dotted_name(sub.func)
                if d:
                    fn.calls.append((d, sub))
                    if d.rsplit(".", 1)[-1] == "_in_trace":
                        fn.has_in_trace_guard = True

        for child in nested:
            self._add_function(child, f"{qual}.{child.name}", None, fn, local)


class ProjectIndex:
    """Symbol table + call graph over every analyzed file."""

    def __init__(self):
        self.functions: Dict[str, FunctionNode] = {}
        self.by_node: Dict[int, str] = {}          # id(ast def) -> qualname
        self.by_name: Dict[str, List[str]] = {}    # bare name -> qualnames
        self.module_level: Dict[str, Dict[str, str]] = {}
        self.methods: Dict[str, Dict[str, Dict[str, str]]] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        self.modules: Set[str] = set()
        self._edges: Dict[Tuple[str, bool], Tuple[str, ...]] = {}
        self._reach: Dict[Tuple[str, bool], Set[str]] = {}

    # -- construction --------------------------------------------------------
    def add_file(self, path: str, tree: ast.Module):
        _FileIndexer(self, path, tree).run()

    # -- resolution ----------------------------------------------------------
    def resolve(self, dotted: str, fn: FunctionNode,
                fallback: bool = True) -> List[str]:
        """Callee qualnames for one dotted call string at one site."""
        parts = dotted.split(".")
        # lexical scope: sibling/enclosing defs, then module-level
        # functions, then the import table (from x import fn)
        if len(parts) == 1:
            q = fn.visible.get(parts[0])
            if q and q in self.functions:
                return [q]
            q = self.module_level.get(fn.module, {}).get(parts[0])
            if q:
                return [q]
            target = self.imports.get(fn.module, {}).get(parts[0])
            if target:
                q = self._resolve_absolute(target.split("."))
                if q:
                    return [q]
            return []
        # self./cls. method of the enclosing class
        if parts[0] in ("self", "cls") and len(parts) == 2:
            holder = self._enclosing_class(fn)
            if holder:
                q = self.methods.get(fn.module, {}).get(holder, {}).get(
                    parts[1])
                if q:
                    return [q]
            return self._fallback(parts[-1]) if fallback else []
        # import-table substitution: alias -> dotted target
        imp = self.imports.get(fn.module, {})
        if parts[0] in imp:
            full = imp[parts[0]].split(".") + parts[1:]
            q = self._resolve_absolute(full)
            if q:
                return [q]
            return self._fallback(parts[-1]) if fallback else []
        # absolute dotted name that starts at a known module
        q = self._resolve_absolute(parts)
        if q:
            return [q]
        return self._fallback(parts[-1]) if fallback else []

    def _enclosing_class(self, fn: FunctionNode) -> Optional[str]:
        if fn.class_name:
            return fn.class_name
        # nested function inside a method: "Cls.meth.inner" -> Cls
        segs = fn.qual.split(".")
        if len(segs) >= 2 and segs[0] in self.methods.get(fn.module, {}):
            return segs[0]
        return None

    def _resolve_absolute(self, parts: List[str]) -> Optional[str]:
        # longest known-module prefix, then fn or Class.method remainder
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod not in self.modules:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                return self.module_level.get(mod, {}).get(rest[0])
            if len(rest) == 2:
                return self.methods.get(mod, {}).get(rest[0], {}).get(rest[1])
            return None
        return None

    def _fallback(self, leaf: str) -> List[str]:
        hits = self.by_name.get(leaf, [])
        return list(hits) if len(hits) == 1 else []

    # -- graph queries -------------------------------------------------------
    def callees(self, qualname: str, fallback: bool = True) -> Tuple[str, ...]:
        key = (qualname, fallback)
        cached = self._edges.get(key)
        if cached is not None:
            return cached
        fn = self.functions.get(qualname)
        out: List[str] = []
        if fn is not None:
            seen = set()
            for dotted, _ in fn.calls:
                for q in self.resolve(dotted, fn, fallback=fallback):
                    if q not in seen and q != qualname:
                        seen.add(q)
                        out.append(q)
            for child in fn.children:       # closures are part of the parent
                if child not in seen:
                    seen.add(child)
                    out.append(child)
        res = tuple(out)
        self._edges[key] = res
        return res

    def reachable(self, qualname: str, fallback: bool = True,
                  stop=None, max_depth: int = 64) -> Set[str]:
        """Functions transitively reachable from ``qualname`` (not
        including itself unless re-entered). ``stop(FunctionNode)`` prunes
        traversal INTO a node (the node is still reported as reached)."""
        if stop is None:
            cached = self._reach.get((qualname, fallback))
            if cached is not None:
                return cached
        seen: Set[str] = set()
        frontier = [(qualname, 0)]
        while frontier:
            cur, depth = frontier.pop()
            if depth >= max_depth:
                continue
            for nxt in self.callees(cur, fallback=fallback):
                if nxt in seen:
                    continue
                seen.add(nxt)
                node = self.functions.get(nxt)
                if stop is not None and node is not None and stop(node):
                    continue
                frontier.append((nxt, depth + 1))
        if stop is None:
            self._reach[(qualname, fallback)] = seen
        return seen

    def node_for(self, ast_def) -> Optional[FunctionNode]:
        q = self.by_node.get(id(ast_def))
        return self.functions.get(q) if q else None


def build_index(ctxs: Iterable) -> ProjectIndex:
    """Index every FileContext (engine pass 0); stored by the Analysis
    runner in ``shared['project_index']`` for all checkers."""
    index = ProjectIndex()
    for ctx in ctxs:
        index.add_file(ctx.path, ctx.tree)
    return index

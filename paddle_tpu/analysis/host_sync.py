"""Runtime host-sync sanitizer: blocking device→host syncs inside step spans.

The static rules (T001/T003) prove no host-sync call is REACHABLE from a
traced function; this is the runtime witness for the eager half, in the
``lock_order.py`` mold. A ``.item()`` / ``np.asarray(device_array)`` /
``block_until_ready`` inside the train-step hot path stalls the device
pipeline: the host blocks on the transfer instead of enqueueing the next
step, and XLA's latency hiding dies silently — the profile shows a slow
step, never the line that caused it. Under ``FLAGS_host_sync_check`` the
sync points are patched to *record* every blocking sync that happens
while a train-step span (``train_step`` / ``forward`` / ``backward`` /
``optimizer`` — the hapi step phases) is open on the current thread, with
the caller's source site, so the suite can assert the hot path stays
sync-free and a regression names its line.

Patched sync points (all transparent pass-throughs):

- ``numpy.asarray`` on a ``jax.Array`` — the funnel ``Tensor.numpy()``,
  ``Tensor.item()``, ``Tensor.__array__`` and ``tolist()`` all drain
  through, so one patch covers the framework's conversion surface;
- ``jax.block_until_ready`` and ``jax.device_get``.

Span tracking rides ``profiler.RecordEvent`` (begin/end wrapped to keep a
per-thread depth of open step spans): collective-lane threads, checkpoint
spans and the data loader are NOT step spans, so their legitimate host
work never records. Module-level imports stay stdlib-only; jax / numpy /
paddle_tpu are imported inside ``install()`` (same contract that lets
``tests/conftest.py`` drive this file without ordering constraints).
"""
from __future__ import annotations

import functools
import os
import sys
import threading
from collections import deque
from typing import List, Optional, Set

__all__ = [
    "STEP_SPAN_NAMES", "HostSyncRecords", "get_records", "install",
    "uninstall", "installed", "in_step_depth", "report",
    "install_future_watch", "uninstall_future_watch", "future_report",
]

# the hapi step phases (model.py train_batch) — the spans whose open
# window means "the device should be ahead of the host right now"
STEP_SPAN_NAMES = frozenset({"train_step", "forward", "backward",
                             "optimizer"})


class HostSyncRecords:
    """Bounded ring of recorded in-step blocking syncs + counters."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=capacity)
        self.total = 0            # in-step syncs recorded (lifetime)
        self.step_spans = 0       # step spans tracked (for the summary)

    def record(self, kind: str, site: str, span: str):
        with self._lock:
            self.total += 1
            self._ring.append({"kind": kind, "site": site, "span": span,
                               "thread": threading.current_thread().name})

    def in_step(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.total = 0

    def report(self) -> dict:
        recs = self.in_step()
        return {
            "in_step_syncs": self.total,
            "step_spans": self.step_spans,
            "sites": sorted({f"{r['kind']} @ {r['site']}" for r in recs}),
            "records": recs,
        }


_records = HostSyncRecords()
_tls = threading.local()
_orig: dict = {}


def get_records() -> HostSyncRecords:
    return _records


def in_step_depth() -> int:
    return getattr(_tls, "depth", 0)


def report() -> dict:
    return _records.report()


def _caller_site() -> str:
    """First stack frame outside this module and numpy — `path:line`,
    shortened to the repo-relative tail when the frame is paddle_tpu's."""
    here = __file__
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if fn != here and f"{os.sep}numpy{os.sep}" not in fn:
            fn = fn.replace(os.sep, "/")
            if "paddle_tpu/" in fn:
                fn = "paddle_tpu/" + fn.split("paddle_tpu/")[-1]
            elif "/" in fn:
                fn = fn.rsplit("/", 1)[-1]
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _open_span() -> Optional[str]:
    spans = getattr(_tls, "spans", None)
    return spans[-1] if spans else None


def _note(kind: str):
    span = _open_span()
    if span is not None:
        _records.record(kind, _caller_site(), span)


def install(step_spans: Optional[Set[str]] = None):
    """Patch the sync points + the span tracker. Idempotent; restores via
    ``uninstall()``. Requires jax/numpy importable (they are wherever a
    train step can run)."""
    if _orig:
        return
    import jax
    import numpy as np

    from ..profiler import RecordEvent

    names = frozenset(step_spans) if step_spans else STEP_SPAN_NAMES
    jax_array_cls = jax.Array

    _orig["np_asarray"] = np.asarray
    _orig["jax_block"] = jax.block_until_ready
    _orig["jax_device_get"] = jax.device_get
    _orig["re_begin"] = RecordEvent.begin
    _orig["re_end"] = RecordEvent.end
    _orig["RecordEvent"] = RecordEvent
    _orig["np"] = np
    _orig["jax"] = jax

    orig_begin, orig_end = RecordEvent.begin, RecordEvent.end

    @functools.wraps(orig_begin)
    def begin(self):
        orig_begin(self)
        if self.name in names:
            spans = getattr(_tls, "spans", None)
            if spans is None:
                spans = _tls.spans = []
            spans.append(self.name)
            _tls.depth = len(spans)
            self._hs_tracked = True
            _records.step_spans += 1

    @functools.wraps(orig_end)
    def end(self):
        if getattr(self, "_hs_tracked", False):
            self._hs_tracked = False
            spans = getattr(_tls, "spans", None)
            if spans:
                # remove the LAST matching name: explicit begin()/end()
                # pairs may misnest just like RecordEvent's own stack
                for i in range(len(spans) - 1, -1, -1):
                    if spans[i] == self.name:
                        del spans[i]
                        break
                _tls.depth = len(spans)
        orig_end(self)

    orig_asarray = np.asarray

    @functools.wraps(orig_asarray)
    def asarray(a, *args, **kwargs):
        if isinstance(a, jax_array_cls) and _open_span() is not None:
            _note("np.asarray")
        return orig_asarray(a, *args, **kwargs)

    orig_block = jax.block_until_ready

    @functools.wraps(orig_block)
    def block_until_ready(x):
        if _open_span() is not None:
            _note("block_until_ready")
        return orig_block(x)

    orig_device_get = jax.device_get

    @functools.wraps(orig_device_get)
    def device_get(x, *args, **kwargs):
        if _open_span() is not None:
            _note("device_get")
        return orig_device_get(x, *args, **kwargs)

    RecordEvent.begin = begin
    RecordEvent.end = end
    np.asarray = asarray
    jax.block_until_ready = block_until_ready
    jax.device_get = device_get
    # the future watch (ISSUE 12) rides the same install path: one flag
    # arms the whole host-side sanitizer family
    install_future_watch()


def uninstall():
    if not _orig:
        return
    _orig["RecordEvent"].begin = _orig["re_begin"]
    _orig["RecordEvent"].end = _orig["re_end"]
    _orig["np"].asarray = _orig["np_asarray"]
    _orig["jax"].block_until_ready = _orig["jax_block"]
    _orig["jax"].device_get = _orig["jax_device_get"]
    _orig.clear()
    uninstall_future_watch()


def installed() -> bool:
    return bool(_orig)


# ---------------------------------------------------------------------------
# future watch: the runtime companion of static rule F002 (ISSUE 12).
# CollectiveLane clients hand out BucketFuture/GatherFuture objects; a
# future created but never awaited is the runtime shape of the leak F002
# proves statically. Under FLAGS_host_sync_check every future's creation,
# first await (wait()/result()/direct _done.wait()) and first resolution
# (_resolve/_fail) is counted per class, and tests/conftest.py prints the
# created-vs-awaited tally next to the lock-order summary at session end.
# ---------------------------------------------------------------------------

_future_counts: dict = {}     # class name -> {created, awaited, resolved}
_future_orig: dict = {}
_fc_lock = threading.Lock()


def _fc(cls_name: str) -> dict:
    with _fc_lock:
        return _future_counts.setdefault(
            cls_name, {"created": 0, "awaited": 0, "resolved": 0})


class _WatchedEvent(threading.Event):
    """threading.Event that counts its first wait (= the future was
    awaited/drained) and first set (= resolved) into the per-class
    tally. BucketFuture drains everywhere go through ``_done`` — fut
    ``wait()``/``result()`` and the flush/abandon/free paths' direct
    ``fut._done.wait()`` alike — so one wrapper covers them all."""

    def __init__(self, counts: dict):
        super().__init__()
        self._counts = counts
        self._waited = False
        self._was_set = False

    def wait(self, timeout=None):
        if not self._waited:
            self._waited = True
            with _fc_lock:
                self._counts["awaited"] += 1
        return super().wait(timeout)

    def set(self):
        if not self._was_set:
            self._was_set = True
            with _fc_lock:
                self._counts["resolved"] += 1
        super().set()


def install_future_watch():
    """Wrap BucketFuture.__init__ (GatherFuture inherits it) so every
    future's ``_done`` event is a counting :class:`_WatchedEvent`.
    Idempotent; requires jax importable (overlap.py imports it)."""
    if _future_orig:
        return
    from ..distributed import overlap

    orig_init = overlap.BucketFuture.__init__
    _future_orig["init"] = orig_init
    _future_orig["cls"] = overlap.BucketFuture

    @functools.wraps(orig_init)
    def init(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        counts = _fc(type(self).__name__)
        with _fc_lock:
            counts["created"] += 1
        watched = _WatchedEvent(counts)
        if self._done.is_set():          # resolved=True constructor path
            watched.set()
        self._done = watched

    overlap.BucketFuture.__init__ = init


def uninstall_future_watch():
    if not _future_orig:
        return
    _future_orig["cls"].__init__ = _future_orig["init"]
    _future_orig.clear()


def future_report() -> dict:
    """{class: {created, awaited, resolved}} plus the leak headline:
    futures neither awaited nor resolved are silent-hang candidates."""
    with _fc_lock:
        classes = {k: dict(v) for k, v in sorted(_future_counts.items())}
    created = sum(c["created"] for c in classes.values())
    awaited = sum(c["awaited"] for c in classes.values())
    resolved = sum(c["resolved"] for c in classes.values())
    return {
        "classes": classes,
        "created": created,
        "awaited": awaited,
        "resolved": resolved,
        "unawaited": max(0, created - awaited),
    }

"""paddle.hub — model hub loader.

Reference: python/paddle/hapi/hub.py (load/list/help over github/gitee/local
sources via hubconf.py). This environment has no network egress, so the
github/gitee sources raise with a clear message and the LOCAL source — a
directory with hubconf.py — is fully supported, which is also the reference's
offline path.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_builtin_list = list


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise NotImplementedError(
            f"hub source {source!r} needs network egress; use source='local' "
            "with a directory containing hubconf.py")


def list(repo_dir, source="local", force_reload=False):
    """Entrypoint names exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate entrypoint `model` from the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"hubconf has no callable entrypoint {model!r}")
    return fn(**kwargs)

"""paddle.device — device management namespace.

Parity: python/paddle/device/__init__.py (set_device:276, get_device,
is_compiled_with_*, cuda submodule). Devices are XLA/PJRT clients.
"""
from __future__ import annotations

from ..framework.device import (  # noqa: F401
    device_count, get_device, is_compiled_with_cuda, set_device,
)

__all__ = ["set_device", "get_device", "device_count", "get_all_device_type",
           "get_all_custom_device_type", "is_compiled_with_cuda",
           "is_compiled_with_xpu", "is_compiled_with_npu",
           "is_compiled_with_tpu", "cuda", "synchronize"]


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_tpu():
    import jax

    try:
        return any("tpu" in d.platform.lower() or
                   "TPU" in getattr(d, "device_kind", "")
                   for d in jax.devices())
    except Exception:
        return False


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return []


def synchronize(device=None):
    """Block until all queued device work completes
    (cudaDeviceSynchronize analog: drain async dispatch)."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


class _Cuda:
    """paddle.device.cuda shims (no CUDA on this stack)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


cuda = _Cuda()

"""paddle.device — device management namespace.

Parity: python/paddle/device/__init__.py (set_device:276, get_device,
is_compiled_with_*, cuda submodule). Devices are XLA/PJRT clients.
"""
from __future__ import annotations

from ..framework.device import (  # noqa: F401
    device_count, get_device, is_compiled_with_cuda, set_device,
)

__all__ = ["set_device", "get_device", "device_count", "get_all_device_type",
           "get_all_custom_device_type", "is_compiled_with_cuda",
           "is_compiled_with_xpu", "is_compiled_with_npu",
           "is_compiled_with_tpu", "cuda", "synchronize", "memory_stats",
           "memory_allocated", "max_memory_allocated", "memory_reserved",
           "max_memory_reserved", "get_device_properties"]


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_tpu():
    import jax

    try:
        return any("tpu" in d.platform.lower() or
                   "TPU" in getattr(d, "device_kind", "")
                   for d in jax.devices())
    except Exception:
        return False


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return []


def synchronize(device=None):
    """Block until all queued device work completes
    (cudaDeviceSynchronize analog: drain async dispatch)."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


def _device_index(device=None):
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    s = str(device)
    return int(s.rsplit(":", 1)[1]) if ":" in s else 0


def memory_stats(device=None) -> dict:
    """HBM statistics for one chip (SURVEY §7: device enumeration + HBM
    stats; reference: memory/stats.h DeviceMemoryStat*). Keys follow PJRT:
    bytes_in_use, peak_bytes_in_use, bytes_limit, largest_free_block_bytes —
    empty dict on backends that don't report (CPU)."""
    import jax

    dev = jax.local_devices()[_device_index(device)]
    try:
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """paddle.device.cuda.memory_allocated analog: live HBM bytes."""
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def max_memory_reserved(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    stats = memory_stats(device)
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def get_device_properties(device=None):
    """Device descriptor (reference: paddle.device.cuda.get_device_properties
    → cudaDeviceProp). Exposes PJRT kind + HBM limit."""
    import jax

    dev = jax.local_devices()[_device_index(device)]
    stats = memory_stats(device)

    class _Props:
        name = getattr(dev, "device_kind", dev.platform)
        platform = dev.platform
        total_memory = int(stats.get("bytes_limit", 0))
        process_index = dev.process_index

        def __repr__(self):
            return (f"DeviceProperties(name={self.name!r}, "
                    f"total_memory={self.total_memory})")

    return _Props()


class _Cuda:
    """paddle.device.cuda shims (no CUDA on this stack)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


cuda = _Cuda()


# ----------------------------------------------------- place/probe parity
from ..framework.device import (  # noqa: E402
    CPUPlace as _CPUPlace,
    XPUPlace,
)

IPUPlace = _CPUPlace   # non-TPU accelerator tags: alias to host place
MLUPlace = _CPUPlace


def is_compiled_with_rocm():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_mlu():
    return False


def is_compiled_with_cinn():
    return False


def get_cudnn_version():
    """No cuDNN in the TPU build (reference returns None when absent)."""
    return None


__all__ += ["IPUPlace", "MLUPlace", "XPUPlace", "is_compiled_with_rocm",
            "is_compiled_with_ipu", "is_compiled_with_mlu",
            "is_compiled_with_cinn", "get_cudnn_version"]

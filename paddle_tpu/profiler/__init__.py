"""paddle.profiler — host + device profiling.

Reference: the new-generation profiler (platform/profiler/ — HostTracer
CommonEvents into an event tree, chrome-trace output_logger.h) and the Python
facade python/paddle/profiler/. TPU device-side tracing is jax.profiler
(XPlane → TensorBoard); host events come from RecordEvent plus a per-op
dispatch hook in call_op (the operator.cc:1264 RecordEvent analog).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional

from ..framework import autograd

__all__ = [
    "Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "SummaryView",
]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView:
    OpView = "op"
    KernelView = "kernel"
    OverView = "overview"


class _Event:
    __slots__ = ("name", "start_ns", "end_ns", "tid", "kind")

    def __init__(self, name, start_ns, end_ns, tid, kind="host"):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.kind = kind


_collector_lock = threading.Lock()
_active_profiler: Optional["Profiler"] = None


class RecordEvent:
    """RAII host-event marker (platform/profiler.cc RecordEvent analog).

    Usable as a context manager or with explicit begin()/end().
    """

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter_ns()

    def end(self):
        if self._t0 is None:
            return
        prof = _active_profiler
        if prof is not None and prof._recording:
            prof._add(_Event(self.name, self._t0, time.perf_counter_ns(),
                             threading.get_ident(), "user"))
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Step-state schedule (parity: paddle.profiler.make_scheduler)."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback writing chrome://tracing JSON."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        prof._export_chrome(path)
        return path

    return handler


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class Profiler:
    """paddle.profiler.Profiler.

    targets including ProfilerTarget.TPU additionally drive jax.profiler
    (XPlane trace for TensorBoard — the CUPTI DeviceTracer analog).
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if isinstance(scheduler, tuple):
            start, end = scheduler
            self.scheduler = make_scheduler(closed=start, ready=0,
                                            record=end - start)
            # paddle's (start, end) means record for steps in [start, end)
            self.scheduler = lambda step: (
                ProfilerState.RECORD if start <= step < end
                else ProfilerState.CLOSED)
        else:
            self.scheduler = scheduler  # callable or None (always record)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.events: List[_Event] = []
        self.step_num = 0
        self._recording = False
        self._prev_hook = None
        self._device_trace_dir = None
        self._step_t0 = None
        self._step_times: List[float] = []

    # -- collection ----------------------------------------------------------
    def _add(self, ev):
        with _collector_lock:
            self.events.append(ev)

    def _op_hook(self, name, t0, t1):
        self._add(_Event(name, t0, t1, threading.get_ident(), "op"))

    def _state(self):
        if self.scheduler is None:
            return ProfilerState.RECORD
        return self.scheduler(self.step_num)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        global _active_profiler
        _active_profiler = self
        self._recording = self._state() in (ProfilerState.RECORD,
                                            ProfilerState.RECORD_AND_RETURN)
        if not self.timer_only:
            self._prev_hook = autograd.set_op_profiler(
                self._op_hook if self._recording else None)
        if ProfilerTarget.TPU in self.targets and not self.timer_only:
            import tempfile

            import jax

            self._device_trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_xplane_")
            try:
                jax.profiler.start_trace(self._device_trace_dir)
            except Exception:
                self._device_trace_dir = None
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        global _active_profiler
        if not self.timer_only:
            autograd.set_op_profiler(self._prev_hook)
        if self._device_trace_dir is not None:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        _active_profiler = None
        self._recording = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        self.step_num += 1
        state = self._state()
        was = self._recording
        self._recording = state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        if not self.timer_only and was != self._recording:
            autograd.set_op_profiler(self._op_hook if self._recording
                                     else None)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- reporting -----------------------------------------------------------
    def _export_chrome(self, path):
        events = []
        for ev in self.events:
            events.append({
                "ph": "X", "cat": ev.kind, "name": ev.name,
                "pid": os.getpid(), "tid": ev.tid,
                "ts": ev.start_ns / 1000.0,
                "dur": (ev.end_ns - ev.start_ns) / 1000.0,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path

    def export(self, path, format="json"):
        if format == "json":
            return self._export_chrome(path)
        raise ValueError(f"unsupported export format {format!r}")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated per-op table (profiler_statistic analog)."""
        unit = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[time_unit]
        agg = {}
        for ev in self.events:
            d = agg.setdefault(ev.name, [0, 0.0, float("inf"), 0.0])
            dur = (ev.end_ns - ev.start_ns) / unit
            d[0] += 1
            d[1] += dur
            d[2] = min(d[2], dur)
            d[3] = max(d[3], dur)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total':>12}{'Min':>10}"
                 f"{'Max':>10}{'Avg':>10}  ({time_unit})"]
        for name, (cnt, tot, mn, mx) in rows:
            lines.append(f"{name[:39]:<40}{cnt:>8}{tot:>12.3f}{mn:>10.3f}"
                         f"{mx:>10.3f}{tot / max(cnt, 1):>10.3f}")
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            lines.append(f"steps: {len(self._step_times)}, "
                         f"avg step time: {avg * 1e3:.3f} ms")
        table = "\n".join(lines)
        print(table)
        return table

    @property
    def device_trace_dir(self):
        """TensorBoard XPlane directory when TPU tracing was on."""
        return self._device_trace_dir

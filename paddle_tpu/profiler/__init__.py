"""paddle.profiler — host + device profiling.

Reference: the new-generation profiler (platform/profiler/ — HostTracer
CommonEvents into an event tree, chrome-trace output_logger.h) and the Python
facade python/paddle/profiler/. TPU device-side tracing is jax.profiler
(XPlane → TensorBoard); host events come from RecordEvent plus a per-op
dispatch hook in call_op (the operator.cc:1264 RecordEvent analog).

Events form a parent-linked span TREE (the HostTracer event-tree analog):
each RecordEvent carries an id and the id of the enclosing RecordEvent on
the same thread, so chrome traces and tools/trace_report.py can reconstruct
nesting instead of guessing from time overlap. Every span end is also
streamed to registered span sinks (observability.StepTimer subscribes to
build per-step phase breakdowns), profiler active or not.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Callable, List, Optional

from ..framework import autograd

__all__ = [
    "Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "SummaryView", "add_span_sink", "remove_span_sink",
]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView:
    OpView = "op"
    KernelView = "kernel"
    OverView = "overview"


class _Event:
    __slots__ = ("name", "start_ns", "end_ns", "tid", "kind", "id",
                 "parent_id")

    def __init__(self, name, start_ns, end_ns, tid, kind="host", eid=None,
                 parent_id=None):
        self.name = name
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.tid = tid
        self.kind = kind
        self.id = eid
        self.parent_id = parent_id


_collector_lock = threading.Lock()
_active_profiler: Optional["Profiler"] = None


def _log_profiler_fault(message: str):
    """Record a swallowed-by-design profiler fault to the event log (with
    traceback) instead of dropping it. Import is lazy and itself guarded:
    the profiler must stay usable even if observability is mid-teardown."""
    try:
        from ..observability.events import get_event_log

        import traceback as _tb
        get_event_log().warning("profiler", message,
                                error=_tb.format_exc(limit=4))
    except Exception:   # lint-ok: C003 last-resort guard; event log itself unavailable
        pass

# per-thread stack of open RecordEvent ids — the parent linkage source
_span_tls = threading.local()
_event_ids = itertools.count(1)

# span sinks: called as sink(name, start_ns, end_ns, tid) on EVERY
# RecordEvent end, whether or not a profiler is recording
# (observability.StepTimer registers here)
_span_sinks: List[Callable] = []


def add_span_sink(sink: Callable) -> Callable:
    _span_sinks.append(sink)
    return sink


def remove_span_sink(sink: Callable):
    try:
        _span_sinks.remove(sink)
    except ValueError:
        pass


def _stack() -> list:
    s = getattr(_span_tls, "stack", None)
    if s is None:
        s = _span_tls.stack = []
    return s


def _current_span_id() -> Optional[int]:
    s = getattr(_span_tls, "stack", None)
    return s[-1] if s else None


class RecordEvent:
    """RAII host-event marker (platform/profiler.cc RecordEvent analog).

    Usable as a context manager or with explicit begin()/end(). Nesting is
    tracked per thread: the event records the id of the RecordEvent it was
    opened inside, forming the span tree.
    """

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._id = None
        self._parent_id = None

    def begin(self):
        self._id = next(_event_ids)
        self._parent_id = _current_span_id()
        _stack().append(self._id)
        self._t0 = time.perf_counter_ns()

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter_ns()
        s = _stack()
        if s and s[-1] == self._id:
            s.pop()
        elif self._id in s:        # misnested explicit begin()/end(): unwind
            del s[s.index(self._id):]
        tid = threading.get_ident()
        prof = _active_profiler
        if prof is not None and prof._recording:
            prof._add(_Event(self.name, self._t0, t1, tid, "user",
                             eid=self._id, parent_id=self._parent_id))
        for sink in _span_sinks:
            try:
                sink(self.name, self._t0, t1, tid)
            except Exception:
                # a broken sink must not sink the training loop — but the
                # fault is recorded, not swallowed (rule C003)
                _log_profiler_fault(f"span sink failed for {self.name!r}")
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Step-state schedule (parity: paddle.profiler.make_scheduler)."""
    cycle = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback writing chrome://tracing JSON. Fires once per
    record cycle (Profiler.step sees RECORD_AND_RETURN end a cycle) and at
    stop(); each export names the file by the profiler's export count so a
    later cycle never overwrites an earlier one."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        n = getattr(prof, "_export_count", 0)
        suffix = f".cycle{n}" if n else ""
        path = os.path.join(dir_name, f"{name}{suffix}.pt.trace.json")
        prof._export_chrome(path)
        return path

    return handler


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class Profiler:
    """paddle.profiler.Profiler.

    targets including ProfilerTarget.TPU additionally drive jax.profiler
    (XPlane trace for TensorBoard — the CUPTI DeviceTracer analog).
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if isinstance(scheduler, tuple):
            # paddle's (start, end) means record for steps in [start, end);
            # going through make_scheduler (rather than a bare lambda) keeps
            # RECORD_AND_RETURN at step end-1, so per-cycle export fires
            start, end = scheduler
            self.scheduler = make_scheduler(closed=start, ready=0,
                                            record=end - start, repeat=1)
        else:
            self.scheduler = scheduler  # callable or None (always record)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.events: List[_Event] = []
        self.step_num = 0
        self._recording = False
        self._prev_hook = None
        self._prev_active = None
        self._device_trace_dir = None
        self._step_t0 = None
        self._step_times: List[float] = []
        self._export_count = 0

    # -- collection ----------------------------------------------------------
    def _add(self, ev):
        with _collector_lock:
            self.events.append(ev)

    def _op_hook(self, name, t0, t1):
        # op events parent under the innermost open RecordEvent (the
        # operator.cc RecordEvent-inside-RecordEvent tree shape)
        self._add(_Event(name, t0, t1, threading.get_ident(), "op",
                         eid=next(_event_ids),
                         parent_id=_current_span_id()))

    def _state(self):
        if self.scheduler is None:
            return ProfilerState.RECORD
        return self.scheduler(self.step_num)

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        global _active_profiler
        with _collector_lock:
            self._prev_active = _active_profiler
            _active_profiler = self
        self._recording = self._state() in (ProfilerState.RECORD,
                                            ProfilerState.RECORD_AND_RETURN)
        if not self.timer_only:
            self._prev_hook = autograd.set_op_profiler(
                self._op_hook if self._recording else None)
        if ProfilerTarget.TPU in self.targets and not self.timer_only:
            import tempfile

            import jax

            self._device_trace_dir = tempfile.mkdtemp(prefix="paddle_tpu_xplane_")
            try:
                jax.profiler.start_trace(self._device_trace_dir)
            except Exception:
                self._device_trace_dir = None
                _log_profiler_fault("device trace start failed")
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        global _active_profiler
        if not self.timer_only:
            autograd.set_op_profiler(self._prev_hook)
        if self._device_trace_dir is not None:
            import jax

            try:
                jax.profiler.stop_trace()
            except Exception:
                _log_profiler_fault("device trace stop failed")
        # nested profilers: restore the enclosing one (hook restore above
        # pairs with this — a nested start/stop must leave the outer
        # profiler collecting exactly as before)
        with _collector_lock:
            _active_profiler, self._prev_active = self._prev_active, None
        self._recording = False
        if self.on_trace_ready is not None and \
                (self.events or self._export_count == 0):
            # skip only when per-cycle exports already flushed everything
            self.on_trace_ready(self)
            self._export_count += 1

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        prev_state = self._state()   # state of the step that just finished
        self.step_num += 1
        state = self._state()
        was = self._recording
        self._recording = state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        if not self.timer_only and was != self._recording:
            autograd.set_op_profiler(self._op_hook if self._recording
                                     else None)
        if prev_state == ProfilerState.RECORD_AND_RETURN and \
                self.on_trace_ready is not None:
            # a record cycle just ended: hand the collected events out NOW
            # (per-cycle export), then clear for the next cycle; without a
            # handler events accumulate for summary()/export() at stop
            self.on_trace_ready(self)
            self._export_count += 1
            with _collector_lock:
                self.events = []

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- reporting -----------------------------------------------------------
    def span_tree(self):
        """Parent-linked event tree: list of root nodes, each
        {"event": _Event, "children": [...]} ordered by start time."""
        nodes = {ev.id: {"event": ev, "children": []}
                 for ev in self.events if ev.id is not None}
        roots = []
        for ev in sorted(self.events, key=lambda e: e.start_ns):
            if ev.id is None:
                continue
            parent = nodes.get(ev.parent_id)
            if parent is not None:
                parent["children"].append(nodes[ev.id])
            else:
                roots.append(nodes[ev.id])
        return roots

    def _export_chrome(self, path):
        events = []
        for ev in self.events:
            rec = {
                "ph": "X", "cat": ev.kind, "name": ev.name,
                "pid": os.getpid(), "tid": ev.tid,
                "ts": ev.start_ns / 1000.0,
                "dur": (ev.end_ns - ev.start_ns) / 1000.0,
            }
            if ev.id is not None:
                rec["args"] = {"id": ev.id, "parent_id": ev.parent_id}
            events.append(rec)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return path

    def export(self, path, format="json"):
        if format == "json":
            return self._export_chrome(path)
        raise ValueError(f"unsupported export format {format!r}")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated per-op table (profiler_statistic analog)."""
        unit = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[time_unit]
        agg = {}
        for ev in self.events:
            d = agg.setdefault(ev.name, [0, 0.0, float("inf"), 0.0])
            dur = (ev.end_ns - ev.start_ns) / unit
            d[0] += 1
            d[1] += dur
            d[2] = min(d[2], dur)
            d[3] = max(d[3], dur)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
        lines = [f"{'Name':<40}{'Calls':>8}{'Total':>12}{'Min':>10}"
                 f"{'Max':>10}{'Avg':>10}  ({time_unit})"]
        for name, (cnt, tot, mn, mx) in rows:
            lines.append(f"{name[:39]:<40}{cnt:>8}{tot:>12.3f}{mn:>10.3f}"
                         f"{mx:>10.3f}{tot / max(cnt, 1):>10.3f}")
        if self._step_times:
            avg = sum(self._step_times) / len(self._step_times)
            lines.append(f"steps: {len(self._step_times)}, "
                         f"avg step time: {avg * 1e3:.3f} ms")
        table = "\n".join(lines)
        print(table)
        return table

    @property
    def device_trace_dir(self):
        """TensorBoard XPlane directory when TPU tracing was on."""
        return self._device_trace_dir

"""Runtime flag registry.

Reference: paddle/fluid/platform/flags.cc (48 PADDLE_DEFINE_EXPORTED gflags) +
python facade paddle.set_flags/get_flags (fluid/framework.py:6846,6870).
TPU-native: most CUDA allocator/cudnn flags are meaningless under PJRT; we keep
the facade, honour the ones with XLA analogs, and accept-and-store the rest so
user scripts keep running.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {
    # sanitizer-style checks (reference: FLAGS_check_nan_inf, operator.cc:1311)
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    # allocator knobs — stored for compat; PJRT owns HBM
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    # determinism
    "FLAGS_cudnn_deterministic": False,
    # executor choice is moot (XLA is the executor) but kept
    "FLAGS_USE_STANDALONE_EXECUTOR": True,
    # eager-op jit cache
    "FLAGS_eager_jit_cache": True,
    # route DataLoader prefetch through the native C++ blocking queue
    # (cross-thread pickle transport; off by default — the in-process Python
    # queue hands batches over zero-copy)
    "FLAGS_use_native_dataloader_queue": False,
    # ---- reference flag tail with TPU analogs (flags.cc families) --------
    # verbosity: FLAGS_v maps onto the framework loggers' level (glog -v)
    "FLAGS_v": 0,
    # host allocator family — PJRT owns HBM; host-side fractions stored for
    # compat (fraction_of_cpu_memory_to_use etc.)
    "FLAGS_fraction_of_cpu_memory_to_use": 1.0,
    "FLAGS_initial_cpu_memory_in_mb": 500,
    "FLAGS_fast_eager_deletion_mode": True,
    "FLAGS_memory_fraction_of_eager_deletion": 1.0,
    "FLAGS_use_pinned_memory": True,
    # determinism family — stored for compat: the eager tape already
    # accumulates gradients in deterministic topological order, so
    # sort_sum_gradient has nothing extra to sort
    "FLAGS_sort_sum_gradient": False,
    "FLAGS_embedding_deterministic": False,
    # host threading — stored for compat (XLA sizes its own thread pool)
    "FLAGS_paddle_num_threads": 1,
    # PS communicator family — read as defaults by Communicator.create /
    # AsyncCommunicator (merge count, queue capacity, wait)
    "FLAGS_communicator_max_merge_var_num": 20,
    "FLAGS_communicator_send_queue_size": 20,
    "FLAGS_communicator_send_wait_times": 0.005,
    # AMP loss scaling floor (min_loss_scaling) — read by GradScaler
    "FLAGS_min_loss_scaling": 1.0,
    # profiler tail: FLAGS_enable_rpc_profiler is WIRED (reinterpreted) —
    # there is no RPC layer here (XLA/PJRT own the wire), so turning it on
    # streams per-collective / distributed-path events into
    # observability.get_event_log() instead (see _apply_rpc_profiler)
    "FLAGS_enable_rpc_profiler": False,
    "FLAGS_max_inplace_grad_add": 0,
    # default per-group timeout for eager collectives, in seconds (analog of
    # the reference's NCCL_BLOCKING_WAIT + new_group(timeout=) default).
    # 0 = disabled: collectives block forever, exactly the seed behavior.
    # Groups created while this is set inherit it (distributed/collective.py
    # new_group); robustness/distributed_ft.py enforces it on eager calls.
    "FLAGS_collective_timeout_s": 0.0,
    # ---- distributed telemetry plane (observability/, ISSUE 6) ----------
    # per-rank live telemetry HTTP endpoint (/metrics /snapshot /events
    # /flightrecorder). 0 = off; any port (use a base port + rank offset on
    # multi-process hosts) is bound by observability.start_exposition(),
    # which hapi's MetricsCallback calls on train begin.
    "FLAGS_telemetry_http_port": 0,
    # flight-recorder ring depth (entries). Read when the global recorder
    # is created (first telemetry/distributed import); 0 disables
    # recording. Reconfigure later with
    # observability.configure_flight_recorder().
    "FLAGS_flight_recorder_capacity": 4096,
    # postmortem dump directory; "" = <tmpdir>/paddle_tpu_flightrec
    "FLAGS_flight_recorder_dir": "",
    # ---- static analysis & sanitizers (analysis/, ISSUE 7) --------------
    # lock-order witness (analysis/lock_order.py): on = framework locks
    # created after the flag is set are wrapped so cross-lock acquisition
    # edges build a graph and ABBA-inversion cycles are reportable
    # (lock_order.get_graph().report()). tests/conftest.py installs it
    # BEFORE paddle_tpu imports when the env var is set, so module-level
    # locks are witnessed too.
    "FLAGS_lock_order_check": False,
    # host-sync sanitizer (analysis/host_sync.py, ISSUE 11): on = the
    # device→host sync points (np.asarray on jax arrays,
    # jax.block_until_ready, jax.device_get) are patched to record any
    # blocking sync that happens while a train-step span is open —
    # host_sync.report() names the offending source lines. Installed by
    # tests/conftest.py when the env var is set; zero overhead when off.
    "FLAGS_host_sync_check": False,
    # device selection handed to worker processes by distributed/launch
    # ("all" or a count) and read back by distributed/env.py. Declared
    # here (registry-drift rule R001) so env override and get_flags see it.
    "FLAGS_selected_tpus": "0",
    # ---- pallas kernel autotuner (ops/pallas/, ISSUE 13) ----------------
    # on = kernel dispatch (flash attention block shapes, quant_matmul
    # tiles, the fused dequant+update bucket tile, the blockwise codec
    # kernels) consults the tune cache (artifacts/kernel_tune_cache.json /
    # .cache/ runtime copy) for validated winners, and the fused-update /
    # codec pallas kernels replace their jnp compositions on TPU targets.
    # Off (default): every dispatch uses today's defaults — numerically
    # dot-for-dot the pre-ISSUE-13 behavior. Observability:
    # kernel_dispatch_total{kernel=,source=tuned|default|fallback}.
    "FLAGS_kernel_autotune": False,
    # ---- continuous-batching serving runtime (serving/, ISSUE 14) ------
    # tokens per paged-KV-cache block (the pool allocation granularity)
    "FLAGS_serving_block_tokens": 16,
    # max sequences decoded together per replica (the continuous batch)
    "FLAGS_serving_max_batch": 8,
    # request-queue admission depth: submits beyond this are REJECTED
    # (open-loop backpressure), counted serve_requests_total{outcome=}
    "FLAGS_serving_queue_depth": 256,
    # at-rest KV-cache codec: "fp32" (bit-exact) | "int8_block" |
    # "fp8_block" (grad_comm blockwise codecs; ~4x less KV HBM)
    "FLAGS_serving_kv_codec": "fp32",
    # per-replica watchdog: a scheduler tick stuck past this many seconds
    # evicts the replica (drain + re-admit its in-flight requests)
    "FLAGS_serving_watchdog_s": 30.0,
    # ---- prefix cache + speculative decode (serving/, ISSUE 16) --------
    # on (default): admission matches prompt prefixes against resident
    # refcounted KV blocks and prefills only the un-cached tail (shared
    # blocks are read-only; copy-on-write before any append; LRU over
    # refcount-0 blocks). Off: every prompt prefills from scratch
    # (pre-ISSUE-16 behavior). Counters:
    # serve_prefix_cache_{hit,miss}_tokens_total.
    "FLAGS_serving_prefix_cache": True,
    # draft tokens proposed per speculative decode step (engines built
    # with a draft_model; losslessly verified against the target —
    # gauge serve_spec_accepted_per_step)
    "FLAGS_serving_spec_k": 4,
    # ---- fleet elastic controller (ISSUE 17) ---------------------------
    # compile-aware watchdog grace: while a replica reports state
    # "compiling" (its first step traces+compiles under jit) the
    # per-replica watchdog deadline stretches to this many seconds, so a
    # cold compile is not evicted as a hang (the PR-14 bug class where a
    # 0.5s watchdog evicted the survivor for compiling)
    "FLAGS_serving_compile_grace_s": 120.0,
    # ---- request-scoped tracing (observability/tracing.py, ISSUE 18) ----
    # on (default): every ServeRequest admission mints a TraceContext and
    # lifecycle edges (queue wait, prefill, decode steps, eviction,
    # requeue, re-admission, retire) record spans into the bounded trace
    # store + the flight-recorder ring; latency/TTFT histogram
    # observations carry the trace id as an exemplar. Off: zero spans,
    # zero exemplars (the serve_bench tracing-overhead phase times both).
    "FLAGS_serving_tracing": True,
    # bounded per-request trace store: max retained traces (oldest
    # evicted) and max spans kept per trace (overflow counted, not kept)
    "FLAGS_trace_store_capacity": 256,
    "FLAGS_trace_max_spans": 256,
    # ---- zero-cold-start plane (jit/artifact_cache.py, ISSUE 19) -------
    # wall-clock budget for a WARM replica boot (standby pre-compiles
    # every shape bucket the set has executed before the old replica
    # drains). Exceeding it raises the typed ReplicaBootBudgetExceeded:
    # the standby is abandoned, the boot falls back to the cold path, and
    # the outcome is recorded replica_boots_total{mode=warm,
    # outcome=warm_boot_timeout} — a slow compile may cost the warm
    # handoff, never hang the fleet.
    "FLAGS_replica_boot_budget_s": 300.0,
    # root directory of the persistent compiled-artifact cache; "" =
    # in-process warm map only (no disk tier)
    "FLAGS_artifact_cache_dir": "",
    # ---- parameter-server hot path (distributed/ps/pipeline.py, ISSUE 20) --
    # in-flight window of the async pull/push pipeline: while step k runs,
    # up to depth-1 later batches may have pulls in flight and up to
    # depth-1 earlier batches may have pushes uncommitted. 1 = fully
    # serial (pull -> step -> push per batch, bit-identical to the
    # unpipelined reference); 2 = classic double buffering
    "FLAGS_ps_pipeline_depth": 2,
    # wire codec for sharded pull/push embedding payloads riding the
    # MessageBus: "fp32" (bit-exact) | "int8_block" | "fp8_block" (the
    # PR-8 blockwise codecs; ~4x less wire, error-feedback residual per
    # table shard on the push side)
    "FLAGS_ps_wire_codec": "fp32",
    # elements per abs-max scale block of the blockwise wire codecs (wider
    # than the collective default: embedding rows tolerate a coarser scale
    # and the fp32 scale vector is pure wire overhead on the PS hop)
    "FLAGS_ps_wire_block": 1024,
    # default shard-host count for make_sharded_ps() when none is given
    "FLAGS_ps_shards": 1,
    # per-attempt timeout for a sharded pull/push RPC, and how many times
    # it retries (exponential backoff) before the shard is declared dead
    "FLAGS_ps_pull_timeout_s": 10.0,
    "FLAGS_ps_pull_retries": 2,
    # behavior after a shard host is declared dead: False (default) =
    # raise the typed DeadShardError (fail fast, PR-4 failure model);
    # True = loud degraded mode — pulls return the table's init rows for
    # that shard's keys, pushes to it are dropped-and-counted
    # (ps_degraded_ops_total{shard=}), and an ERROR event names the host
    "FLAGS_ps_degraded_ok": False,
}

_compat_warned: set = set()


def _env_override():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ("1", "true", "yes")
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            else:
                _FLAGS[k] = v
    if "FLAGS_v" in os.environ:  # env-set verbosity must also apply
        _apply_verbosity(int(_FLAGS["FLAGS_v"]))
    if "FLAGS_enable_rpc_profiler" in os.environ:  # env-set wiring too
        _apply_rpc_profiler(bool(_FLAGS["FLAGS_enable_rpc_profiler"]))
    if _FLAGS.get("FLAGS_lock_order_check"):
        _apply_lock_order_check()
    if _FLAGS.get("FLAGS_host_sync_check"):
        _apply_host_sync_check()


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags({'FLAGS_check_nan_inf': True})."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict")
    for k, v in flags.items():
        _FLAGS[k] = v
    if flags.get("FLAGS_check_nan_inf") or flags.get("FLAGS_cudnn_deterministic"):
        _apply_debug_flags()
    if "FLAGS_v" in flags:
        _apply_verbosity(int(flags["FLAGS_v"]))
    if "FLAGS_enable_rpc_profiler" in flags:
        _apply_rpc_profiler(bool(flags["FLAGS_enable_rpc_profiler"]))
    if flags.get("FLAGS_lock_order_check"):
        _apply_lock_order_check()
    if flags.get("FLAGS_host_sync_check"):
        _apply_host_sync_check()


def _apply_lock_order_check():
    """FLAGS_lock_order_check: install the lock-order witness. Locks
    created from here on are instrumented; for module-level locks set the
    env var instead so tests/conftest.py installs before paddle_tpu
    imports."""
    from ..analysis import lock_order

    lock_order.install()


def _apply_host_sync_check():
    """FLAGS_host_sync_check: install the host-sync sanitizer (patches
    np.asarray / jax.block_until_ready / jax.device_get + the step-span
    tracker). Idempotent; host_sync.uninstall() restores."""
    from ..analysis import host_sync

    host_sync.install()


def _apply_rpc_profiler(on: bool):
    """FLAGS_enable_rpc_profiler (reference: per-RPC spans in the fluid
    distributed/ps runtime). No RPC stack exists here, so the flag is
    REINTERPRETED rather than dropped: on = distributed collectives and ps
    pushes emit structured records into observability.get_event_log().
    A one-time compat warning spells out the reinterpretation."""
    import warnings

    from ..observability import enable_rpc_event_log

    if on and "FLAGS_enable_rpc_profiler" not in _compat_warned:
        _compat_warned.add("FLAGS_enable_rpc_profiler")
        warnings.warn(
            "flags.FLAGS_enable_rpc_profiler: there is no RPC layer on this "
            "stack (XLA/PJRT own the wire); the flag is reinterpreted — "
            "per-collective events now stream into "
            "paddle_tpu.observability.get_event_log()", stacklevel=3)
    enable_rpc_event_log(on)


def _apply_verbosity(v: int):
    """glog -v analog: raise framework logger verbosity (0 = warnings,
    1 = info, >=2 = debug)."""
    import logging

    level = (logging.WARNING if v <= 0
             else logging.INFO if v == 1 else logging.DEBUG)
    logging.getLogger("paddle_tpu").setLevel(level)


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def flag(name: str, default=None):
    return _FLAGS.get(name, default)


def _apply_debug_flags():
    import jax

    if _FLAGS.get("FLAGS_check_nan_inf"):
        jax.config.update("jax_debug_nans", True)


# applied at import so env-set flags (incl. FLAGS_v) take effect immediately
_env_override()

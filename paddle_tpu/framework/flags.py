"""Runtime flag registry.

Reference: paddle/fluid/platform/flags.cc (48 PADDLE_DEFINE_EXPORTED gflags) +
python facade paddle.set_flags/get_flags (fluid/framework.py:6846,6870).
TPU-native: most CUDA allocator/cudnn flags are meaningless under PJRT; we keep
the facade, honour the ones with XLA analogs, and accept-and-store the rest so
user scripts keep running.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {
    # sanitizer-style checks (reference: FLAGS_check_nan_inf, operator.cc:1311)
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    # allocator knobs — stored for compat; PJRT owns HBM
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    # determinism
    "FLAGS_cudnn_deterministic": False,
    # executor choice is moot (XLA is the executor) but kept
    "FLAGS_USE_STANDALONE_EXECUTOR": True,
    # eager-op jit cache
    "FLAGS_eager_jit_cache": True,
    # route DataLoader prefetch through the native C++ blocking queue
    # (cross-thread pickle transport; off by default — the in-process Python
    # queue hands batches over zero-copy)
    "FLAGS_use_native_dataloader_queue": False,
}


def _env_override():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ("1", "true", "yes")
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            else:
                _FLAGS[k] = v


_env_override()


def set_flags(flags: Dict[str, Any]):
    """paddle.set_flags({'FLAGS_check_nan_inf': True})."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict")
    for k, v in flags.items():
        _FLAGS[k] = v
    if flags.get("FLAGS_check_nan_inf") or flags.get("FLAGS_cudnn_deterministic"):
        _apply_debug_flags()


def get_flags(flags) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def flag(name: str, default=None):
    return _FLAGS.get(name, default)


def _apply_debug_flags():
    import jax

    if _FLAGS.get("FLAGS_check_nan_inf"):
        jax.config.update("jax_debug_nans", True)

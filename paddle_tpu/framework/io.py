"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:553,769
— pickled state_dict with large-object protocol handling).

Saves are ATOMIC by default (write-to-temp + fsync + rename via
robustness/checkpoint.py): a crash mid-save leaves the previous file intact
instead of a torn pickle. Loads raise typed framework errors
(CheckpointNotFoundError / CheckpointCorruptError) instead of surfacing a
raw pickle traceback.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, atomic=True, **configs):
    """Serialize a Tensor / state_dict / nested structure to disk.

    atomic=True (default) commits via temp-file + fsync + rename, so readers
    (and a post-crash restart) see either the old or the new content, never
    a torn mix. `configs` may carry `fs=` (a robustness LocalFS-like object)
    for fault-injection tests.
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    data = pickle.dumps(_to_saveable(obj), protocol=protocol)
    if atomic:
        from ..robustness.checkpoint import atomic_write

        atomic_write(path, data, fs=configs.get("fs"))
    else:
        with open(path, "wb") as f:
            f.write(data)


def load(path, **configs):
    """Load an object saved by paddle.save. Arrays come back as np.ndarray
    (accepted everywhere a Tensor is: set_state_dict, set_value)."""
    from .errors import CheckpointCorruptError, CheckpointNotFoundError

    if not os.path.exists(path):
        raise CheckpointNotFoundError(
            f"no checkpoint at {path!r} (expected a paddle.save pickle, "
            f"e.g. '*.pdparams'/'*.pdopt'). If an interrupted save produced "
            f"this path, the commit never landed — "
            f"robustness.CheckpointManager.load_latest() falls back to the "
            f"newest valid checkpoint.")
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (pickle.UnpicklingError, EOFError, AttributeError, IndexError,
            KeyError, ValueError) as e:
        raise CheckpointCorruptError(
            f"failed to deserialize {path!r}: {e!r}. The checkpoint may be "
            f"partial (torn write from a crash mid-save) — see "
            f"robustness.CheckpointManager.load_latest() for "
            f"corruption-skipping resume.") from e

"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:553,769
— pickled state_dict with large-object protocol handling)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from .tensor import Tensor


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Serialize a Tensor / state_dict / nested structure to disk."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """Load an object saved by paddle.save. Arrays come back as np.ndarray
    (accepted everywhere a Tensor is: set_state_dict, set_value)."""
    with open(path, "rb") as f:
        return pickle.load(f)

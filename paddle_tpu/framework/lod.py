"""LoD (level-of-detail) ragged-tensor machinery.

Reference: paddle/fluid/framework/lod_tensor.h:33 (`using LoDTensor =
pten::DenseTensor` carrying a LoD), lod_tensor.h:36-40
(SplitLoDTensor/MergeLoDTensor), python/paddle/fluid/lod_tensor.py
(create_lod_tensor / create_random_int_lodtensor).

TPU-native design: XLA wants static shapes, so ragged data lives in ONE of
two forms and converts at the host boundary, exactly where the reference's
sequence_pad/unpad CUDA ops sit:

  * LoDTensor — host container: flat rows (all sequences concatenated on
    axis 0) + recursive sequence lengths (nested python lists). This is the
    feed/fetch and io format, API-compatible with the reference.
  * carrier   — device format: (padded [B, T, ...], lengths [B]) consumed
    by every op in nn/functional/sequence.py and by RNNs.

The LoD itself is host metadata (the reference also manipulates it on CPU);
only dense data ever reaches the chip.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = [
    "LoDTensor", "create_lod_tensor", "create_random_int_lodtensor",
    "split_lod_tensor", "merge_lod_tensor",
]


def _lengths_to_offsets(lengths: Sequence[int]) -> List[int]:
    out = [0]
    for n in lengths:
        out.append(out[-1] + int(n))
    return out


def _offsets_to_lengths(offsets: Sequence[int]) -> List[int]:
    return [int(offsets[i + 1] - offsets[i]) for i in range(len(offsets) - 1)]


class LoDTensor:
    """Ragged tensor: flat concatenated rows + recursive sequence lengths.

    `recursive_sequence_lengths` is the reference's length-based LoD: a list
    of levels, outermost first; level i's entries sum to the number of
    entries at level i+1 (innermost level sums to shape[0] of the data).
    `lod()` returns the equivalent offset-based form.
    """

    def __init__(self, data=None, recursive_seq_lens=None):
        self._data = None if data is None else np.asarray(data)
        self._seq_lens: List[List[int]] = [
            [int(n) for n in level] for level in (recursive_seq_lens or [])
        ]

    # -- reference API surface ------------------------------------------------
    def set(self, data, place=None):
        self._data = np.asarray(data)

    def lod(self) -> List[List[int]]:
        """Offset-based LoD (reference LoDTensor::lod)."""
        return [_lengths_to_offsets(lv) for lv in self._seq_lens]

    def set_lod(self, lod) -> None:
        self._seq_lens = [_offsets_to_lengths(lv) for lv in lod]

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(lv) for lv in self._seq_lens]

    def set_recursive_sequence_lengths(self, seq_lens) -> None:
        self._seq_lens = [[int(n) for n in lv] for lv in seq_lens]

    def has_valid_recursive_sequence_lengths(self) -> bool:
        """Level i must have sum(level i) == len(level i+1); the innermost
        level must sum to data.shape[0] (reference CheckLoD)."""
        if self._data is None:
            return False
        levels = self._seq_lens
        for i, lv in enumerate(levels):
            if any(n < 0 for n in lv):
                return False
            total = sum(lv)
            if i + 1 < len(levels):
                if total != len(levels[i + 1]):
                    return False
            elif total != self._data.shape[0]:
                return False
        return True

    @property
    def shape(self):
        return tuple(self._data.shape) if self._data is not None else ()

    def numpy(self) -> np.ndarray:
        return self._data

    def __array__(self, dtype=None):
        a = self._data
        return a if dtype is None else a.astype(dtype)

    def __repr__(self):
        return (f"LoDTensor(shape={self.shape}, "
                f"recursive_sequence_lengths={self._seq_lens})")

    # -- TPU carrier conversions ---------------------------------------------
    def innermost_lengths(self) -> List[int]:
        """Sequence lengths at the innermost (row) level."""
        if not self._seq_lens:
            return [self._data.shape[0]] if self._data is not None else []
        return list(self._seq_lens[-1])

    def to_carrier(self, maxlen=None, pad_value=0):
        """(padded [B, T, ...], lengths [B]) numpy pair — the device format
        every sequence op consumes (the reference's sequence_pad_op)."""
        if self._data is None:
            raise ValueError("LoDTensor has no data")
        lens = np.asarray(self.innermost_lengths(), np.int64)
        B = lens.size
        T = int(maxlen if maxlen is not None else (lens.max() if B else 0))
        feat = self._data.shape[1:]
        padded = np.full((B, T) + feat, pad_value, dtype=self._data.dtype)
        off = 0
        for b, n in enumerate(lens):
            n = min(int(n), T)
            padded[b, :n] = self._data[off:off + n]
            off += int(lens[b])
        return padded, lens

    @classmethod
    def from_carrier(cls, padded, lengths) -> "LoDTensor":
        """Inverse of to_carrier (the reference's sequence_unpad_op)."""
        padded = np.asarray(padded)
        lens = [int(n) for n in np.asarray(lengths).reshape(-1)]
        rows = [padded[b, :n] for b, n in enumerate(lens)]
        flat = (np.concatenate(rows, axis=0) if rows else
                padded.reshape((0,) + padded.shape[2:]))
        return cls(flat, [lens])


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """Reference: python/paddle/fluid/lod_tensor.py create_lod_tensor.

    data may be a numpy array / nested list of rows / another LoDTensor.
    """
    if isinstance(data, LoDTensor):
        return LoDTensor(data.numpy(), recursive_seq_lens)
    if isinstance(data, (list, tuple)) and data and isinstance(
            data[0], (list, tuple, np.ndarray)):
        flat = np.concatenate([np.asarray(r).reshape(len(r), -1)
                               for r in data], axis=0)
        t = LoDTensor(flat, recursive_seq_lens)
        if not t.has_valid_recursive_sequence_lengths():
            raise ValueError(
                f"recursive_seq_lens {recursive_seq_lens} inconsistent with "
                f"input data rows {flat.shape[0]}")
        return t
    t = LoDTensor(np.asarray(data), recursive_seq_lens)
    if not t.has_valid_recursive_sequence_lengths():
        raise ValueError(
            f"recursive_seq_lens {recursive_seq_lens} inconsistent with "
            f"input shape {t.shape}")
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1) -> LoDTensor:
    """Reference: fluid/lod_tensor.py create_random_int_lodtensor."""
    rows = sum(recursive_seq_lens[-1])
    shape = (rows,) + tuple(base_shape)
    data = np.random.randint(low, high + 1, size=shape, dtype=np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)


def split_lod_tensor(x: LoDTensor, n: int) -> List[LoDTensor]:
    """Split along the outermost sequence level into n chunks for
    multi-device feed (reference SplitLoDTensor, lod_tensor.h:36)."""
    lens = x.innermost_lengths()
    B = len(lens)
    if x.recursive_sequence_lengths() and len(
            x.recursive_sequence_lengths()) > 1:
        raise NotImplementedError(
            "split_lod_tensor supports single-level LoD")
    data = x.numpy()
    offsets = _lengths_to_offsets(lens)
    out = []
    per = (B + n - 1) // n
    for i in range(n):
        lo, hi = i * per, min((i + 1) * per, B)
        if lo >= hi:
            out.append(LoDTensor(data[:0], [[]]))
            continue
        out.append(LoDTensor(data[offsets[lo]:offsets[hi]],
                             [lens[lo:hi]]))
    return out


def merge_lod_tensor(parts: Sequence[LoDTensor]) -> LoDTensor:
    """Inverse of split_lod_tensor (reference MergeLoDTensor)."""
    datas = [p.numpy() for p in parts if p.numpy() is not None
             and p.numpy().shape[0] >= 0]
    lens: List[int] = []
    for p in parts:
        lens.extend(p.innermost_lengths())
    flat = np.concatenate([d for d in datas], axis=0) if datas else None
    return LoDTensor(flat, [lens])

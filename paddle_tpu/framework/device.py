"""Device / Place abstraction.

Reference: paddle/fluid/platform/place.h (CPUPlace/CUDAPlace/...),
python/paddle/device/__init__.py:276 (set_device). TPU-native: a Place wraps a
jax.Device; there are no streams or per-device contexts to manage — XLA/PJRT owns
scheduling. We keep a process-global current place used by creation ops.
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Tagged device identity. Compares by (kind, index)."""

    kind = "unknown"

    def __init__(self, index: int = 0):
        self.index = int(index)

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # Fall back to whatever the default backend exposes (e.g. CPU-only CI).
            devs = jax.devices()
        return devs[min(self.index, len(devs) - 1)]

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    kind = "tpu"


class CUDAPlace(Place):
    """Accepted for API compatibility; resolves to the accelerator backend."""

    kind = "tpu"


class XPUPlace(Place):
    """Accepted for API compatibility; resolves to the accelerator backend."""

    kind = "tpu"


# axon/tpu-like platforms all count as "tpu" for Place purposes.
_ACCEL_PLATFORMS = ("tpu", "axon")


def _kind_of(dev: jax.Device) -> str:
    plat = dev.platform
    if plat in _ACCEL_PLATFORMS:
        return "tpu"
    return plat


@functools.lru_cache(maxsize=None)
def _default_place() -> Place:
    for d in jax.devices():
        if _kind_of(d) == "tpu":
            return TPUPlace(0)
    return CPUPlace(0)


_CURRENT: list = []


def set_device(device) -> Place:
    """paddle.set_device('tpu') / 'cpu' / 'tpu:0'."""
    place = _parse(device)
    _CURRENT[:] = [place]
    return place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    if _CURRENT:
        return _CURRENT[0]
    return _default_place()


def _parse(device) -> Place:
    if isinstance(device, Place):
        return device
    if isinstance(device, jax.Device):
        cls = TPUPlace if _kind_of(device) == "tpu" else CPUPlace
        return cls(device.id)
    if not isinstance(device, str):
        raise ValueError(f"Cannot parse device {device!r}")
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name in ("tpu", "gpu", "cuda", "xpu", "npu", "ipu", "mlu", "axon"):
        return TPUPlace(idx)
    if name == "cpu":
        return CPUPlace(idx)
    raise ValueError(f"Unknown device {device!r}")


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(jax.devices())

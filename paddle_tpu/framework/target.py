"""Compile-target platform resolution.

`jax.default_backend()` answers "where do eager arrays live", which is the
wrong question for code choosing a lowering: under ahead-of-time
compilation (jit/aot.py, jax.experimental.topologies) arrays live on CPU
while the compile TARGET is a described TPU slice. Kernels that branch on
the platform — pallas interpret mode, the flash-attention gate — must ask
"what platform is this program being compiled FOR":

  1. an explicit `force_target(...)` override, if active (rarely needed);
  2. else the ACTIVE MESH's device platform (a topology mesh of described
     TPU chips answers "tpu" even in a CPU-backend process);
  3. else jax.default_backend() (eager/single-device: target == default).

Reference contrast: the reference resolves this with per-kernel registration
keyed by the Place of the execution context (framework/operator.cc kernel
key selection) — place and backend never diverge there because programs are
interpreted per-op on live devices. AOT compilation for absent hardware is
what makes the distinction exist here.
"""
from __future__ import annotations

import contextlib
import threading

import jax

_tls = threading.local()

__all__ = ["target_platform", "force_target"]


def target_platform() -> str:
    override = getattr(_tls, "override", None)
    if override is not None:
        return override
    try:
        from ..distributed import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        if m is not None and m.devices.size:
            return m.devices.flat[0].platform
    except (ImportError, AttributeError, RuntimeError):
        # mesh probe is best-effort by contract: during early import (the
        # distributed package may be mid-initialization) or with a torn
        # mesh we fall back to jax's default backend — any other fault
        # should surface, not vanish (rule C003)
        pass
    return jax.default_backend()


@contextlib.contextmanager
def force_target(platform: str):
    """Pin target_platform() for this thread (e.g. compiling a single-chip
    program for a described TPU without putting a mesh around it)."""
    prev = getattr(_tls, "override", None)
    _tls.override = platform
    try:
        yield
    finally:
        _tls.override = prev

"""Tensor: paddle-semantics wrapper over an immutable jax.Array.

Reference: pten::DenseTensor (pten/core/dense_tensor.h:41) + imperative VarBase
(imperative/layer.h:66). Paddle Tensors are mutable, carry ``stop_gradient``
(default True; Parameters default False) and a ``.grad`` accumulated by
``backward()``. TPU-native: the payload is an immutable ``jax.Array``; mutation
(in-place ops, ``set_value``, ``__setitem__``) rebinds ``_value`` — under jit
tracing the payload is a tracer, which is how the functional bridge
(paddle_tpu.jit) threads state.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, device as device_mod, dtype as dtype_mod


def _default_sharding(place):
    """Placement for new tensors: the requested device, or — when a multi-device
    mesh is active — replicated over the mesh so eager ops compose with
    mesh-sharded parameters."""
    if place is None:
        try:
            from ..distributed import mesh as mesh_mod

            m = mesh_mod.get_mesh()
            if m is not None and m.size > 1:
                from jax.sharding import NamedSharding, PartitionSpec

                return NamedSharding(m, PartitionSpec())
        except ImportError:
            pass
        place = device_mod.current_place()
    return place.jax_device


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "_hooks",
        "__weakref__",
        "__dict__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True, _internal=False):
        if _internal:
            # fast path: data is already a jax value (possibly a tracer)
            self._value = data
        else:
            dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
            if isinstance(data, Tensor):
                val = data._value
                if dt is not None and val.dtype != dt:
                    val = val.astype(dt)
                self._value = val
            elif isinstance(data, (jax.Array, jax.core.Tracer)):
                # already a device value (possibly a tracer inside jit/shard_map)
                self._value = data.astype(dt) if dt is not None and data.dtype != dt else data
            else:
                arr = np.asarray(data)
                if dt is None and arr.dtype == np.float64:
                    dt = dtype_mod.get_default_dtype()
                if dt is not None:
                    arr = arr.astype(dt)
                sharding = _default_sharding(place)
                self._value = jax.device_put(arr, sharding)
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node = None
        self._out_index = 0
        self.name = ""
        self.persistable = False
        self._hooks = []

    # ------------------------------------------------------------- properties
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if devs is None:
            return device_mod.current_place()
        try:
            dev = next(iter(self._value.devices()))
        except Exception:
            return device_mod.current_place()
        return device_mod._parse(dev)

    @property
    def T(self):
        from .. import tensor as ops

        return ops.transpose(self, list(range(self.ndim))[::-1])

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    # ------------------------------------------------------------ conversions
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None, copy=None):
        # np.asarray(tensor) gets the dense values, not an object array
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return autograd.call_op(lambda x: x.astype(dt), self, op_name="cast")

    def cast(self, dtype):
        return self.astype(dtype)

    def clone(self):
        return autograd.call_op(lambda x: x + 0, self, op_name="clone")

    def detach(self):
        t = Tensor(self._value, _internal=True)
        t.stop_gradient = True
        t.name = self.name
        return t

    def cpu(self):
        t = Tensor(jax.device_put(self._value, device_mod.CPUPlace(0).jax_device), _internal=True)
        t.stop_gradient = self.stop_gradient
        return t

    def cuda(self, *a, **k):  # compat: accelerator == tpu
        t = Tensor(jax.device_put(self._value, device_mod.current_place().jax_device), _internal=True)
        t.stop_gradient = self.stop_gradient
        return t

    def to(self, *args, **kwargs):
        out = self
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "tpu", "gpu", "cuda"):
                out = Tensor(
                    jax.device_put(out._value, device_mod._parse(a).jax_device), _internal=True
                )
                out.stop_gradient = self.stop_gradient
            else:
                out = out.astype(a)
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            out = out.astype(kwargs["dtype"])
        return out

    def pin_memory(self):
        return self

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.run_backward(
            [self],
            [grad_tensor] if grad_tensor is not None else None,
            retain_graph=retain_graph,
        )

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Handle:
            def remove(handle_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)

        return _Handle()

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # ----------------------------------------------------------- mutation
    def set_value(self, value):
        """In-place overwrite (reference: VarBase SetValue). Rebinds the payload."""
        if isinstance(value, Tensor):
            val = value._value
        else:
            val = jnp.asarray(value)
        if tuple(val.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {val.shape} vs {self._value.shape}"
            )
        self._value = val.astype(self._value.dtype)
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    def fill_(self, v):
        self._value = jnp.full_like(self._value, v)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    def _replace_from(self, new: "Tensor"):
        """Adopt value + autograd identity from ``new`` (for in-place-with-grad)."""
        self._value = new._value
        self._grad_node = new._grad_node
        self._out_index = new._out_index
        self.stop_gradient = new.stop_gradient

    # ----------------------------------------------------------- indexing
    def __getitem__(self, idx):
        idx = _sanitize_index(idx)
        return autograd.call_op(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        idx = _sanitize_index(idx)
        if isinstance(value, Tensor):
            new = autograd.call_op(
                lambda x, v: x.at[idx].set(v.astype(x.dtype)), self, value, op_name="setitem"
            )
        else:
            new = autograd.call_op(
                lambda x: x.at[idx].set(jnp.asarray(value).astype(x.dtype)),
                self,
                op_name="setitem",
            )
        self._replace_from(new)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ----------------------------------------------------------- operators
    def __bool__(self):
        return builtins_bool(self.numpy())

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __index__(self):
        return int(self.item())

    def __hash__(self):
        return id(self)

    def __repr__(self):
        sg = self.stop_gradient
        try:
            vals = np.array2string(self.numpy(), precision=8, separator=", ")
        except Exception:
            vals = f"<traced {self._value}>"
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}, "
            f"place={self.place}, stop_gradient={sg},\n       {vals})"
        )

    __str__ = __repr__

    # dunder arithmetic is monkey-patched from paddle_tpu.tensor (math_op_patch
    # analog: fluid/dygraph/math_op_patch.py)


def _sanitize_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)) and not isinstance(i, (str, bytes)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


import builtins as _builtins  # noqa: E402

builtins_bool = _builtins.bool


class Parameter(Tensor):
    """Trainable Tensor (reference: framework::Parameter, fluid/framework.py).

    ``stop_gradient`` defaults False; carries optional distributed attrs:
    ``.is_distributed`` and a jax ``PartitionSpec`` in ``.dist_spec`` consumed
    by the pjit bridge.
    """

    def __init__(self, data, dtype=None, name="", trainable=True):
        super().__init__(data, dtype=dtype)
        self.stop_gradient = not trainable
        self.name = name
        self.persistable = True
        self.is_distributed = False
        self.dist_spec = None

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor (reference: python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor) and dtype is None and place is None:
        t = Tensor(data._value, _internal=True)
        t.stop_gradient = stop_gradient
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.create_parameter (reference: fluid/layers/tensor.py
    create_parameter). Delegates to Layer.create_parameter so ParamAttr
    handling (trainable/initializer/name/need_clip) and initializer
    defaults stay in one place; no explicit Program registration is needed
    — a build-time Program adopts the parameter as an external the first
    time an op consumes it."""
    from ..framework.param_attr import ParamAttr
    from ..nn import Layer

    if name is not None and attr is None:
        attr = ParamAttr(name=name)
    p = Layer().create_parameter(shape, attr=attr, dtype=dtype,
                                 is_bias=is_bias,
                                 default_initializer=default_initializer)
    return p

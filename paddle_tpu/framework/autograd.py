"""Eager autograd engine over jax VJPs.

Reference behavior: paddle/fluid/imperative/{tracer.cc,basic_engine.cc,
gradient_accumulator.cc} — ``Tracer::TraceOp`` records a ``GradOpNode`` per op;
``loss.backward()`` runs a reverse-topological walk accumulating gradients.

TPU-native design: instead of per-op grad kernels, every functional kernel is a
pure jax function; at dispatch time (``call_op``) we take ``jax.vjp`` of the
function over its differentiable Tensor inputs. That computes the forward *once*
(vjp returns primal outputs + a pullback closure holding residuals on device)
and records a ``GradNode``. ``backward()`` is a Kahn walk over GradNodes calling
the pullbacks — the analog of BasicEngine::Execute's queue over GradOpNode.

The fast path (whole-step ``jax.jit``) does not use this tape at all: to_static
traces the forward functionally and differentiates with ``jax.grad``.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_tls = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


def _set_grad_enabled(v: bool):
    _tls.grad_enabled = v


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad — usable as context manager or decorator."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = is_grad_enabled()
        _set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._prev)
        return False


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = is_grad_enabled()
    _set_grad_enabled(bool(mode))
    try:
        yield
    finally:
        _set_grad_enabled(prev)


class GradNode:
    """One recorded op: pullback + which Tensors its cotangents flow to.

    ``inputs`` snapshots each input's producing node at record time — the tape
    must route cotangents through the graph as it existed when the op ran, not
    as it looks after a later in-place rebind of the same Tensor (otherwise
    ``y = x*2; x[0] = 5; y.backward()`` would send y's cotangent through the
    setitem node and corrupt gradients).
    """

    __slots__ = (
        "vjp_fn",
        "inputs",
        "out_avals",
        "multi_output",
        "pending",
        "name",
        "released",
        "replay",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, multi_output, name="",
                 replay=None):
        self.vjp_fn = vjp_fn
        # list[(Tensor, producer GradNode|None, out_index)] aligned with the
        # pullback's cotangent outputs
        self.inputs = inputs
        self.out_avals = out_avals  # list[ShapeDtypeStruct]
        self.multi_output = multi_output
        self.pending: Dict[int, Any] = {}
        self.name = name
        self.released = False
        # (fn, args, kwargs, tensor_pos, diff_j) when the op can be replayed
        # differentiably for create_graph (double grad)
        self.replay = replay

    def seed(self, idx: int, cot):
        cur = self.pending.get(idx)
        self.pending[idx] = cot if cur is None else cur + cot

    def release(self):
        self.vjp_fn = None
        self.inputs = None
        self.pending = {}
        self.replay = None
        self.released = True


_FLOATING_DTYPES: Dict[Any, bool] = {}


def _is_floating(val) -> bool:
    # dtype-keyed cache: issubdtype costs ~2us and runs per tensor per op
    # on the eager hot path
    dt = getattr(val, "dtype", None)
    if dt is None:
        dt = jnp.result_type(val)
    hit = _FLOATING_DTYPES.get(dt)
    if hit is None:
        hit = _FLOATING_DTYPES[dt] = bool(
            jnp.issubdtype(dt, jnp.floating)
            or jnp.issubdtype(dt, jnp.complexfloating))
    return hit


# Static-graph recorder hook (paddle_tpu.static): while a Program is being
# built, every dispatched op is also appended to its tape. The analog of
# OpDesc emission under program_guard (reference: fluid/framework.py
# append_op); replay happens in static.Executor as one jitted function.
_op_recorder = None

# Profiler hook (paddle_tpu.profiler): when active, called as
# hook(op_name, start_ns, end_ns) after each eager dispatch — the analog of
# the RecordEvent wrap around compute (reference: operator.cc:1264).
_op_profiler = None

# Grad-ready hook (distributed/overlap.py): when set, run_backward calls
# hook(tensor) the moment a LEAF tensor's gradient is final — every
# contribution deposited, no more edges pending — which is the reference
# Reducer's "variable ready" signal (imperative/reducer.cc MarkVarReady).
# The overlap layer uses it to launch a gradient bucket's collective while
# the rest of backward is still running.
_grad_ready_hook = None


def set_grad_ready_hook(hook):
    """Install the leaf-grad-ready callback; returns the previous one so
    callers can restore it (the overlap layer installs per backward)."""
    global _grad_ready_hook
    prev = _grad_ready_hook
    _grad_ready_hook = hook
    return prev


# Value materializer (distributed/sharding/stage3.py): under ZeRO-3 a
# parameter freed after use carries a FreedParamValue placeholder instead
# of a jax array. A dispatch that still reaches it (a tied weight read
# outside its owning layer's forward) must re-materialize the value —
# jax.jit rejects foreign objects, it does not consult __array__. When a
# materializer is installed, every dispatched input value passes through
# it; unset (the default), the hot path pays one module-global None check.
_value_materializer = None


def set_value_materializer(fn):
    """Install the freed-value materializer; returns the previous one."""
    global _value_materializer
    prev = _value_materializer
    _value_materializer = fn
    return prev

# Dispatch telemetry (observability.MetricsRegistry): pre-bound Counter
# objects so the hot path pays one attribute add per event, no registry
# lookup. trace-cache hit/miss tracks _OPCACHE (a miss = a fresh jax trace
# + jit compile — the number the EQuARX-style step-time audits need).
from ..observability.metrics import get_registry as _get_registry

_m_dispatch = _get_registry().counter(
    "eager_dispatch_total", help="eager ops dispatched through call_op",
).bind()
_m_cache_hit = _get_registry().counter(
    "trace_cache_hits_total", help="eager op-cache hits (no retrace)",
).bind()
_m_cache_miss = _get_registry().counter(
    "trace_cache_misses_total",
    help="eager op-cache misses (fresh trace+jit)").bind()
_m_uncacheable = _get_registry().counter(
    "trace_cache_uncacheable_total",
    help="dispatches with no cache key (dynamic closure/static args)",
).bind()


def set_op_recorder(recorder):
    global _op_recorder
    prev = _op_recorder
    _op_recorder = recorder
    return prev


def set_op_profiler(hook):
    global _op_profiler
    prev = _op_profiler
    _op_profiler = hook
    return prev


# ---------------------------------------------------------------------------
# eager op-cache (SURVEY §7 hard part 1: "aggressive eager compilation cache")
#
# Reference precedent: the eager final-state dygraph dispatches pre-registered
# kernels per op; here each call_op would otherwise re-TRACE fn via jax.vjp on
# every dispatch. The cache holds, per (fn code+closure, static args, input
# shapes/dtypes, diff positions), a jitted forward and a jitted backward
# (which recomputes the forward inside the vjp — rematerialized residuals
# trade a little FLOP for not keeping a Python pullback per call). Keys are
# only formed from whitelisted static closure/arg values, so fresh lambdas
# over arrays (uncacheable) transparently use the direct path.
# ---------------------------------------------------------------------------

_OPCACHE: Dict[Any, Any] = {}
_OPCACHE_CAP = 2048


def _static_ok(v) -> bool:
    if isinstance(v, (int, float, bool, str, bytes, type(None))):
        return True
    if isinstance(v, (tuple, frozenset)):
        return all(_static_ok(x) for x in v)
    if isinstance(v, (np.dtype, type)):
        return True
    return False


def _op_cache_key(fn, args, tensor_pos, kwargs, vals, diff_j, op_name):
    code = getattr(fn, "__code__", None)
    ident = code if code is not None else fn
    cells = ()
    closure = getattr(fn, "__closure__", None)
    if closure:
        cells = tuple(c.cell_contents for c in closure)
        if not all(_static_ok(c) for c in cells):
            return None
    defaults = getattr(fn, "__defaults__", None)
    if defaults and not all(_static_ok(d) for d in defaults):
        return None
    static_args = tuple(a for i, a in enumerate(args) if i not in tensor_pos)
    if not all(_static_ok(a) for a in static_args):
        return None
    kw = tuple(sorted(kwargs.items()))
    if not all(_static_ok(v) for _, v in kw):
        return None
    # np.dtype is hashable; str(dtype) costs ~3us/tensor on the hot path
    sig = tuple((v.shape, v.dtype) for v in vals)
    return (ident, cells, defaults, static_args, kw, sig, tuple(diff_j),
            op_name)


class _OpCacheEntry:
    __slots__ = ("fwd", "bwd")


def _make_cache_entry(fn, args, tensor_pos, kwargs, diff_j):
    snapshot = [None if i in set(tensor_pos) else a for i, a in enumerate(args)]

    def assemble(vals):
        full = list(snapshot)
        for j, i in enumerate(tensor_pos):
            full[i] = vals[j]
        return full

    def fwd(vals):
        return fn(*assemble(vals), **kwargs)

    entry = _OpCacheEntry()
    entry.fwd = jax.jit(fwd)
    if diff_j:
        def bwd(vals, cots):
            def closure(*dvals):
                merged = list(vals)
                for j, dv in zip(diff_j, dvals):
                    merged[j] = dv
                return fn(*assemble(merged), **kwargs)

            _, vjp_fn = jax.vjp(closure, *[vals[j] for j in diff_j])
            return vjp_fn(cots)

        entry.bwd = jax.jit(bwd)
    else:
        entry.bwd = None
    return entry


def _opcache_get(key, fn, args, tensor_pos, kwargs, diff_j):
    entry = _OPCACHE.get(key)
    if entry is None:
        _m_cache_miss.value += 1
        if len(_OPCACHE) >= _OPCACHE_CAP:
            _OPCACHE.pop(next(iter(_OPCACHE)))
        entry = _OPCACHE[key] = _make_cache_entry(
            fn, args, tensor_pos, kwargs, tuple(diff_j))
    else:
        _m_cache_hit.value += 1
    return entry


def clear_op_cache():
    _OPCACHE.clear()


def call_op(fn: Callable, *args, op_name: str = "", **kwargs):
    """Dispatch a functional kernel with optional tape recording.

    ``fn`` is a pure function taking raw jax values in the positions where
    Tensors appear in ``args``. Returns Tensor (or tuple of Tensors).
    The analog of Tracer::TraceOp (imperative/tracer.cc:157).
    """
    from .tensor import Tensor

    _m_dispatch.value += 1
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    vals = [args[i]._value for i in tensor_pos]
    if _value_materializer is not None:
        # ZeRO-3 freed-parameter self-heal (stage3.py): swap any freed
        # placeholder for its re-gathered device value before dispatch
        vals = [_value_materializer(v) for v in vals]

    # AMP O1/O2 input casting (reference: imperative/amp_auto_cast.cc)
    from ..amp import amp_cast_inputs, amp_state

    if amp_state() is not None:
        vals = amp_cast_inputs(op_name, vals)

    diff_j = []
    if is_grad_enabled():
        for j, i in enumerate(tensor_pos):
            t = args[i]
            if not t.stop_gradient and _is_floating(t._value):
                diff_j.append(j)

    def assemble(merged_vals):
        full = list(args)
        for j, i in enumerate(tensor_pos):
            full[i] = merged_vals[j]
        return full

    # compiled-cache lookup (None key → direct path)
    ckey = None
    if _op_recorder is None:  # static capture needs the raw fn, not a jit
        ckey = _op_cache_key(fn, args, tensor_pos, kwargs, vals, diff_j,
                             op_name)
        if ckey is None:
            _m_uncacheable.value += 1

    if not diff_j:
        if _op_profiler is not None:
            import time as _time

            t0 = _time.perf_counter_ns()
            if ckey is not None:
                entry = _opcache_get(ckey, fn, args, tensor_pos, kwargs, diff_j)
                out = entry.fwd(tuple(vals))
            else:
                out = fn(*assemble(vals), **kwargs)
            _op_profiler(op_name or getattr(fn, "__name__", "op"), t0,
                         _time.perf_counter_ns())
        elif ckey is not None:
            entry = _opcache_get(ckey, fn, args, tensor_pos, kwargs, diff_j)
            out = entry.fwd(tuple(vals))
        else:
            out = fn(*assemble(vals), **kwargs)
        res = _wrap_outputs(out, node=None, op_name=op_name)
        if _op_recorder is not None:
            _op_recorder(fn, args, kwargs, res, op_name)
        return res

    def closure(*dvals):
        merged = list(vals)
        for j, dv in zip(diff_j, dvals):
            merged[j] = dv
        return fn(*assemble(merged), **kwargs)

    primals = tuple(vals[j] for j in diff_j)

    def _dispatch():
        if ckey is None:
            return jax.vjp(closure, *primals)
        entry = _opcache_get(ckey, fn, args, tensor_pos, kwargs, diff_j)
        outs = entry.fwd(tuple(vals))
        vals_t = tuple(vals)

        def cached_vjp(cot):
            leaves = jax.tree_util.tree_leaves(cot)
            if any(getattr(c, "dtype", None) == jax.dtypes.float0
                   for c in leaves):
                # integer-output cotangents (float0) don't pass through jit;
                # retrace this rare case directly
                _, vjp_fn = jax.vjp(closure, *primals)
                return vjp_fn(cot)
            return entry.bwd(vals_t, cot)

        return outs, cached_vjp

    if _op_profiler is not None:
        import time as _time

        t0 = _time.perf_counter_ns()
        outs, vjp_fn = _dispatch()
        _op_profiler(op_name or getattr(fn, "__name__", "op"), t0,
                     _time.perf_counter_ns())
    else:
        outs, vjp_fn = _dispatch()

    multi = isinstance(outs, (tuple, list))
    out_list = list(outs) if multi else [outs]
    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_list]
    node = GradNode(
        vjp_fn,
        [
            (args[tensor_pos[j]], args[tensor_pos[j]]._grad_node,
             args[tensor_pos[j]]._out_index)
            for j in diff_j
        ],
        out_avals,
        multi,
        name=op_name or getattr(fn, "__name__", "op"),
        # snapshot: static (non-tensor) args + forward-time tensor VALUES
        # (post-AMP-cast, matching vjp_fn's residuals); no Tensor refs so
        # stop-grad/int inputs are not pinned beyond their values
        replay=(fn,
                tuple(None if i in set(tensor_pos) else a
                      for i, a in enumerate(args)),
                kwargs, tuple(tensor_pos), tuple(diff_j), tuple(vals)),
    )
    res = _wrap_outputs(outs, node=node, op_name=op_name)
    if _op_recorder is not None:
        _op_recorder(fn, args, kwargs, res, op_name)
    return res


def _debug_check_outputs(out, op_name):
    """FLAGS_check_nan_inf / FLAGS_benchmark per-op modes (reference:
    operator.cc:1300 benchmark sync + :1311 CheckOpHasNanOrInf). Only
    consulted when a flag is on; eager values only (tracers are covered by
    jax_debug_nans via set_flags)."""
    from .flags import _FLAGS

    vals = out if isinstance(out, (tuple, list)) else (out,)
    if _FLAGS.get("FLAGS_benchmark"):
        jax.block_until_ready([v for v in vals if hasattr(v, "dtype")])
    if _FLAGS.get("FLAGS_check_nan_inf"):
        for v in vals:
            if (hasattr(v, "dtype") and not isinstance(v, jax.core.Tracer)
                    and jnp.issubdtype(v.dtype, jnp.floating)):
                if bool(jnp.any(~jnp.isfinite(v))):
                    raise FloatingPointError(
                        f"operator {op_name!r} produced nan/inf "
                        "(FLAGS_check_nan_inf)")


def _wrap_outputs(out, node, op_name=""):
    from .flags import _FLAGS
    from .tensor import Tensor

    if _FLAGS.get("FLAGS_check_nan_inf") or _FLAGS.get("FLAGS_benchmark"):
        _debug_check_outputs(out, op_name)
    if isinstance(out, (tuple, list)):
        res = []
        for i, o in enumerate(out):
            t = Tensor(o, _internal=True)
            if node is not None and _is_floating(o):
                t.stop_gradient = False
                t._grad_node = node
                t._out_index = i
            res.append(t)
        return tuple(res)
    t = Tensor(out, _internal=True)
    if node is not None and _is_floating(out):
        t.stop_gradient = False
        t._grad_node = node
        t._out_index = 0
    return t


def run_backward(
    tensors: Sequence,
    grad_tensors: Optional[Sequence] = None,
    retain_graph: bool = False,
    collect: Optional[List] = None,
    accumulate: bool = True,
):
    """Reverse-topological gradient propagation (BasicEngine::Execute analog).

    If ``collect`` is given (a list of Tensors), returns their gradients in
    order (paddle.grad semantics) instead of/in addition to accumulating into
    ``.grad`` when ``accumulate``.
    """
    from .tensor import Tensor

    collect_map: Dict[int, Any] = {}
    collect_ids = {id(t) for t in collect} if collect else set()

    # grad-ready notification (distributed/overlap.py): when a hook is
    # installed and grads actually accumulate, count how many deposit edges
    # each leaf will receive; the hook fires on the deposit that brings a
    # leaf's pending count to zero — its .grad is final from then on
    ready_hook = _grad_ready_hook if accumulate else None
    pending_leaf: Optional[Dict[int, int]] = {} if ready_hook else None

    def deposit(t, g):
        _deposit(t, g, collect_ids, collect_map, accumulate)
        if pending_leaf is None:
            return
        n_left = pending_leaf.get(id(t), 1) - 1
        pending_leaf[id(t)] = n_left
        if n_left <= 0 and not t.stop_gradient and t.grad is not None:
            try:
                ready_hook(t)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "grad-ready hook failed; backward continues")

    # --- seed ---
    roots: List[GradNode] = []
    direct: List = []   # node-less seeds, deposited after counts are known
    for k, t in enumerate(tensors):
        g = None if grad_tensors is None else grad_tensors[k]
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar Tensor requires grad_tensors"
                )
            g = jnp.ones_like(t._value)
        elif isinstance(g, Tensor):
            g = g._value
        node = t._grad_node
        if node is None:
            direct.append((t, g))
        else:
            if node.released:
                raise RuntimeError(
                    "Trying to backward through the graph a second time "
                    "(set retain_graph=True if you need to)"
                )
            node.seed(t._out_index, g)
            roots.append(node)

    # --- build reachable graph & consumer counts ---
    indeg: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    # dedupe: two outputs of one multi-output op seed the SAME node; pushing
    # it twice would double-count its producers' indegree and starve them
    stack = list({id(n): n for n in roots}.values())
    for n in stack:
        nodes.setdefault(id(n), n)
        indeg.setdefault(id(n), 0)
    while stack:
        n = stack.pop()
        for t, p, _oi in n.inputs:
            if p is None or p is n:
                continue
            indeg[id(p)] = indeg.get(id(p), 0) + 1
            if id(p) not in nodes:
                nodes[id(p)] = p
                stack.append(p)

    if pending_leaf is not None:
        # expected deposit edges per leaf: one per node-less seed plus one
        # per reachable node input that deposits directly (p None / self)
        for t, _g in direct:
            pending_leaf[id(t)] = pending_leaf.get(id(t), 0) + 1
        for n in nodes.values():
            for t, p, _oi in n.inputs:
                if p is None or p is n:
                    pending_leaf[id(t)] = pending_leaf.get(id(t), 0) + 1
    for t, g in direct:
        deposit(t, g)

    # --- Kahn walk ---
    ready = [n for n in nodes.values() if indeg.get(id(n), 0) == 0]
    processed = set()
    while ready:
        n = ready.pop()
        if id(n) in processed:
            continue
        processed.add(id(n))
        cots = []
        for i, av in enumerate(n.out_avals):
            c = n.pending.get(i)
            if c is not None and hasattr(c, "dtype") and c.dtype != av.dtype and jnp.issubdtype(
                av.dtype, jnp.floating
            ):
                # AMP: consumer may have upcast the value; pullback wants the
                # producer's dtype
                c = c.astype(av.dtype)
            if c is None:
                if jnp.issubdtype(av.dtype, jnp.floating) or jnp.issubdtype(
                    av.dtype, jnp.complexfloating
                ):
                    c = jnp.zeros(av.shape, av.dtype)
                else:
                    # non-differentiable output (e.g. argmax indices): jax
                    # pullbacks expect a float0 cotangent for integer primals
                    c = np.zeros(av.shape, jax.dtypes.float0)
            cots.append(c)
        n.pending = {}  # reset so a retained graph starts clean next backward
        cot = tuple(cots) if n.multi_output else cots[0]
        grads_in = n.vjp_fn(cot)
        for (t, p, oi), g in zip(n.inputs, grads_in):
            for hook in t._hooks:
                out = hook(Tensor(g, _internal=True))
                if out is not None:
                    g = out._value if isinstance(out, Tensor) else out
            if p is None or p is n:
                deposit(t, g)
            else:
                p.seed(oi, g)
                indeg[id(p)] -= 1
                if indeg[id(p)] == 0:
                    ready.append(p)
        if not retain_graph:
            n.release()

    if collect:
        out = []
        for t in collect:
            g = collect_map.get(id(t))
            out.append(Tensor(g, _internal=True) if g is not None else None)
        return out
    return None


def _replay_node_grads(n, cot_tensors):
    """Differentiable pullback for create_graph: re-derive the node's vjp
    THROUGH call_op, so the produced grads are tape-recorded Tensors whose
    graph reaches both the op's inputs and the incoming cotangents
    (reference: double-grad ops emitted by grad_op_desc_maker).

    Inputs are reconstructed from the FORWARD-TIME value snapshot with the
    record-time tape linkage (GradNode docstring invariant: later in-place
    rebinds of the same Tensor must not change this op's gradients)."""
    from .tensor import Tensor

    fn, static_args, kwargs, tensor_pos, diff_j, snap_vals = n.replay
    float_out = [i for i, av in enumerate(n.out_avals)
                 if jnp.issubdtype(av.dtype, jnp.floating)
                 or jnp.issubdtype(av.dtype, jnp.complexfloating)]
    avals = list(n.out_avals)
    multi = n.multi_output

    def grad_fn(*vals):
        n_in = len(tensor_pos)
        in_vals = list(vals[:n_in])
        cot_vals = list(vals[n_in:])

        def closure(*dvals):
            merged = list(in_vals)
            for j, dv in zip(diff_j, dvals):
                merged[j] = dv
            full = list(static_args)
            for j, i in enumerate(tensor_pos):
                full[i] = merged[j]
            return fn(*full, **kwargs)

        primals = tuple(in_vals[j] for j in diff_j)
        _, vjp = jax.vjp(closure, *primals)
        full_cots = []
        it = iter(cot_vals)
        for i, av in enumerate(avals):
            if i in float_out:
                full_cots.append(next(it))
            else:
                full_cots.append(np.zeros(av.shape, jax.dtypes.float0))
        cot = tuple(full_cots) if multi else full_cots[0]
        out = vjp(cot)
        return tuple(out) if len(out) > 1 else out[0]

    # snapshot tensors: values from forward time; diff positions carry the
    # record-time producer linkage from node.inputs
    linkage = {j: trip for j, trip in zip(diff_j, n.inputs)}
    arg_tensors = []
    snap_to_orig = {}
    for j, v in enumerate(snap_vals):
        t = Tensor(v, _internal=True)
        if j in linkage:
            orig, prod, oi = linkage[j]
            t.stop_gradient = False
            t._grad_node = prod
            t._out_index = oi
            snap_to_orig[id(t)] = orig
        arg_tensors.append(t)
    res = call_op(grad_fn, *arg_tensors, *cot_tensors, op_name=f"grad_{n.name}")
    outs = list(res) if isinstance(res, tuple) else [res]
    # retarget the recorded grad-op's input entries from the snapshot
    # wrappers to the ORIGINAL tensors (deposit/collect match by identity)
    gnode = next((o._grad_node for o in outs
                  if getattr(o, "_grad_node", None) is not None), None)
    if gnode is not None and gnode.inputs:
        gnode.inputs = [
            (snap_to_orig.get(id(t), t), p, oi) for (t, p, oi) in gnode.inputs
        ]
    return outs


def _run_backward_create_graph(tensors, grad_tensors, collect):
    """Tensor-mode Kahn walk: cotangents are live Tensors and every node
    pullback is itself recorded on the tape (double grad)."""
    from .tensor import Tensor

    collect_map: Dict[int, Any] = {}
    collect_ids = {id(t) for t in collect} if collect else set()

    def as_tensor(g):
        return g if isinstance(g, Tensor) else Tensor(jnp.asarray(g),
                                                      _internal=True)

    roots: List[GradNode] = []
    pending: Dict[int, Dict[int, Any]] = {}

    def seed_t(node, idx, g):
        slot = pending.setdefault(id(node), {})
        cur = slot.get(idx)
        slot[idx] = g if cur is None else cur + g

    def deposit_t(t, g):
        if id(t) in collect_ids:
            cur = collect_map.get(id(t))
            collect_map[id(t)] = g if cur is None else cur + g

    for k, t in enumerate(tensors):
        g = None if grad_tensors is None else grad_tensors[k]
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar Tensor requires grad_tensors")
            g = Tensor(jnp.ones_like(t._value), _internal=True)
        else:
            g = as_tensor(g)
        node = t._grad_node
        if node is None:
            deposit_t(t, g)
        else:
            if node.released:
                raise RuntimeError(
                    "Trying to backward through the graph a second time "
                    "(set retain_graph=True if you need to)")
            seed_t(node, t._out_index, g)
            roots.append(node)

    indeg: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    # dedupe: two outputs of one multi-output op seed the SAME node; pushing
    # it twice would double-count its producers' indegree and starve them
    stack = list({id(n): n for n in roots}.values())
    for n in stack:
        nodes.setdefault(id(n), n)
        indeg.setdefault(id(n), 0)
    while stack:
        n = stack.pop()
        for t, p, _oi in n.inputs:
            if p is None or p is n:
                continue
            indeg[id(p)] = indeg.get(id(p), 0) + 1
            if id(p) not in nodes:
                nodes[id(p)] = p
                stack.append(p)

    ready = [n for n in nodes.values() if indeg.get(id(n), 0) == 0]
    processed = set()
    while ready:
        n = ready.pop()
        if id(n) in processed:
            continue
        processed.add(id(n))
        if n.replay is None:
            raise NotImplementedError(
                f"create_graph=True cannot differentiate through op "
                f"{n.name!r} (no differentiable replay); ops dispatched "
                "outside call_op do not support double grad")
        slot = pending.get(id(n), {})
        cot_tensors = []
        for i, av in enumerate(n.out_avals):
            if not (jnp.issubdtype(av.dtype, jnp.floating)
                    or jnp.issubdtype(av.dtype, jnp.complexfloating)):
                continue
            c = slot.get(i)
            if c is None:
                c = Tensor(jnp.zeros(av.shape, av.dtype), _internal=True)
            elif c._value.dtype != av.dtype:
                # cast THROUGH the tape: a detached rebuild would zero
                # higher-order derivatives across mixed-dtype edges
                c = call_op(lambda v: v.astype(av.dtype), c,
                            op_name="grad_cast")
            cot_tensors.append(c)
        pending.pop(id(n), None)
        grads_in = _replay_node_grads(n, cot_tensors)
        for (t, p, oi), g in zip(n.inputs, grads_in):
            for hook in t._hooks:
                out = hook(g)
                if out is not None:
                    g = out if isinstance(out, Tensor) else as_tensor(out)
            if p is None or p is n:
                deposit_t(t, g)
            else:
                seed_t(p, oi, g)
                indeg[id(p)] -= 1
                if indeg[id(p)] == 0:
                    ready.append(p)
        # create_graph implies the graph survives for the next-order pass

    if collect:
        return [collect_map.get(id(t)) for t in collect]
    return None


def _deposit(t, g, collect_ids, collect_map, accumulate):
    from .tensor import Tensor

    if id(t) in collect_ids:
        cur = collect_map.get(id(t))
        collect_map[id(t)] = g if cur is None else cur + g
    if accumulate and not t.stop_gradient:
        if t.grad is None:
            t.grad = Tensor(g, _internal=True)
        else:
            t.grad._value = t.grad._value + g


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
):
    """paddle.grad (reference: imperative/partial_grad_engine.cc).

    create_graph=True returns gradients that are themselves on the tape
    (each pullback replayed differentiably through call_op), so a second
    grad()/backward() computes true higher-order derivatives — the
    reference's double-grad op path (grad_op_desc_maker)."""
    from .tensor import Tensor

    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    if create_graph:
        res = _run_backward_create_graph(outputs, grad_outputs, inputs)
        if not allow_unused:
            for t, g in zip(inputs, res):
                if g is None:
                    raise RuntimeError(
                        "one of the inputs received no gradient "
                        "(allow_unused=False)")
        return res
    res = run_backward(
        outputs,
        grad_outputs,
        retain_graph=bool(retain_graph),
        collect=inputs,
        accumulate=False,
    )
    if not allow_unused:
        for t, g in zip(inputs, res):
            if g is None:
                raise RuntimeError(
                    "One of the differentiated Tensors appears to not have "
                    "been used in the graph (set allow_unused=True to allow)"
                )
    return res

"""Dtype system.

Paddle exposes dtypes as ``paddle.float32`` etc. (reference:
python/paddle/framework/dtype.py, paddle/fluid/framework.py convert_np_dtype_to_dtype_).
Here a dtype is simply a canonical numpy dtype usable directly by jax; we provide
the paddle-style names plus conversion helpers.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtype objects (numpy dtypes — what jax uses natively).
bool = np.dtype("bool")  # noqa: A001 - mirrors paddle.bool
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_STR_ALIASES = {
    "bool": bool,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
    "complex64": complex64,
    "complex128": complex128,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}


_NARROW = {  # x64-disabled jax silently truncates these; do it explicitly
    np.dtype("int64"): int32,
    np.dtype("uint64"): np.dtype("uint32"),
    np.dtype("float64"): float32,
    np.dtype("complex128"): complex64,
}


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np.dtype, jnp type, Tensor dtype) to np.dtype.

    64-bit types narrow to 32-bit unless jax x64 mode is on — int64 indices and
    fp64 math are not TPU-native; this keeps dtype reporting honest instead of
    relying on jax's silent truncation.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            d = _STR_ALIASES[dtype]
        except KeyError:
            try:
                d = jnp.dtype(dtype)
            except TypeError:
                raise ValueError(f"Unknown dtype string: {dtype!r}")
    else:
        try:
            d = jnp.dtype(dtype)
        except TypeError:
            raise ValueError(f"Cannot convert {dtype!r} to a dtype")
    import jax

    if not jax.config.jax_enable_x64:
        d = _NARROW.get(d, d)
    return d


def dtype_name(dtype) -> str:
    d = convert_dtype(dtype)
    return d.name


def is_floating_point(dtype) -> builtins_bool:  # type: ignore[name-defined]
    return convert_dtype(dtype) in _FLOATING


def is_integer(dtype):
    d = convert_dtype(dtype)
    return d in _INTEGER or d == bool


def is_complex(dtype):
    return convert_dtype(dtype) in _COMPLEX


# keep a python-bool alias for annotations above
import builtins as _builtins  # noqa: E402

builtins_bool = _builtins.bool

_DEFAULT_DTYPE = [float32]


def set_default_dtype(d):
    """paddle.set_default_dtype (reference: python/paddle/framework/framework.py)."""
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {d}")
    _DEFAULT_DTYPE[0] = d


def get_default_dtype():
    return _DEFAULT_DTYPE[0]

"""Framework core: dtype, device, Tensor, autograd, RNG, flags."""
from . import autograd, device, dtype, flags, random  # noqa: F401
from .autograd import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, current_place, device_count, get_device,
    is_compiled_with_cuda, is_compiled_with_tpu, set_device,
)
from .dtype import (  # noqa: F401
    bfloat16, bool, complex64, complex128, convert_dtype, float16, float32,
    float64, get_default_dtype, int8, int16, int32, int64, set_default_dtype,
    uint8,
)
from .flags import get_flags, set_flags  # noqa: F401
from . import errors  # noqa: F401
from .random import get_cuda_rng_state, seed, set_cuda_rng_state  # noqa: F401
from .tensor import Parameter, Tensor, to_tensor  # noqa: F401

"""Structured error types.

Reference: paddle/fluid/platform/enforce.h + errors.h — PADDLE_ENFORCE
raises typed exceptions (InvalidArgument, NotFound, OutOfRange, ...) carrying
the failing condition. Python surface: paddle.base.core.* error classes.

Here the types subclass the natural Python exceptions so existing
``except ValueError`` code keeps working while typed handling
(`except errors.InvalidArgumentError`) matches the reference taxonomy.
"""
from __future__ import annotations

__all__ = [
    "InvalidArgumentError", "NotFoundError", "OutOfRangeError",
    "AlreadyExistsError", "ResourceExhaustedError",
    "PreconditionNotMetError", "PermissionDeniedError",
    "ExecutionTimeoutError", "UnimplementedError", "UnavailableError",
    "FatalError", "CheckpointNotFoundError", "CheckpointCorruptError",
    "CheckpointGeometryError",
    "CollectiveTimeoutError", "TransientCollectiveError",
    "ReplicaDivergenceError", "enforce",
]


class InvalidArgumentError(ValueError):
    """errors.h InvalidArgument"""


class NotFoundError(KeyError):
    """errors.h NotFound"""


class OutOfRangeError(IndexError):
    """errors.h OutOfRange"""


class AlreadyExistsError(ValueError):
    """errors.h AlreadyExists"""


class ResourceExhaustedError(MemoryError):
    """errors.h ResourceExhausted"""


class PreconditionNotMetError(RuntimeError):
    """errors.h PreconditionNotMet"""


class PermissionDeniedError(PermissionError):
    """errors.h PermissionDenied"""


class ExecutionTimeoutError(TimeoutError):
    """errors.h ExecutionTimeout"""


class UnimplementedError(NotImplementedError):
    """errors.h Unimplemented"""


class UnavailableError(RuntimeError):
    """errors.h Unavailable"""


class FatalError(SystemError):
    """errors.h Fatal"""


class CheckpointNotFoundError(NotFoundError, FileNotFoundError):
    """paddle.load target does not exist. Also a FileNotFoundError so
    pre-existing ``except FileNotFoundError`` callers keep working."""


class CheckpointCorruptError(UnavailableError):
    """Checkpoint exists but fails deserialization or checksum validation
    (torn write from a crash mid-save, truncation, bit rot)."""


class CheckpointGeometryError(PreconditionNotMetError):
    """A sharded checkpoint's sharding geometry (world size) differs from
    the live job's. Carries both worlds so the caller can opt into the
    elastic N→M reshard transform (distributed/sharding/reshard.py —
    ``allow_reshard=True`` on load_sharded / restore_job_state) instead of
    refusing the resume."""

    def __init__(self, message="", *, from_world=None, to_world=None):
        super().__init__(message)
        self.from_world = from_world
        self.to_world = to_world


class CollectiveTimeoutError(ExecutionTimeoutError):
    """An eager collective exceeded its group timeout (a peer is hung or
    dead). Carries the group/op/rank context a supervisor needs to decide
    between relaunch and shrink (robustness/distributed_ft.py)."""

    def __init__(self, message="", *, op=None, group=None, rank=None,
                 timeout=None, attempt=None):
        super().__init__(message)
        self.op = op
        self.group = group
        self.rank = rank
        self.timeout = timeout
        self.attempt = attempt


class TransientCollectiveError(UnavailableError):
    """A collective failed in a way that is expected to succeed on retry
    (flaky interconnect, preempted peer mid-rejoin). The fault-tolerance
    layer retries these with exponential backoff before giving up."""


class ReplicaDivergenceError(FatalError):
    """Cross-replica integrity check failed: the replicas' parameter
    digests disagree — silent data corruption or DP desync. Carries the
    digests so postmortems can identify the minority rank."""

    def __init__(self, message="", *, step=None, local=None, agreed_min=None,
                 agreed_max=None):
        super().__init__(message)
        self.step = step
        self.local = local
        self.agreed_min = agreed_min
        self.agreed_max = agreed_max


def enforce(condition, message="", error_cls=InvalidArgumentError):
    """PADDLE_ENFORCE analog: raise `error_cls(message)` unless condition."""
    if not condition:
        raise error_cls(message)

"""Stateful RNG over jax's functional PRNG.

Reference: paddle.seed (python/paddle/framework/random.py) and the dygraph RNG
state tracker used for TP-consistent dropout
(distributed/fleet/meta_parallel/parallel_layers/random.py).

Eager code wants a global stateful generator; jit-traced code must not bake
randomness into the compiled program. ``next_key()`` therefore consults a
context-local *provider* first: the jit/to_static bridge installs a provider
that folds a traced key, so compiled programs stay randomness-correct across
steps; outside a trace we split a process-global key.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class Generator:
    """A stateful PRNG stream (splittable).

    Key creation is lazy: ``jax.random.key`` dispatches to the backend, and a
    module-level Generator must not force backend init at ``import paddle_tpu``
    (the driver's ``dryrun_multichip`` needs to pick its platform first).
    """

    def __init__(self, seed: int = 0):
        self._key = None
        self._seed = int(seed)

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.key(int(seed))
        self._host_rng = None  # host-side stream (io.random_split) re-derives
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        self._key, sub = jax.random.split(self.key)
        return sub

    def get_state(self):
        return jax.random.key_data(self.key)

    def set_state(self, state):
        self._key = jax.random.wrap_key_data(np.asarray(state))
        self._host_rng = None  # restored state restores host-stream determinism


_global = Generator(0)
_tls = threading.local()

# Host-side numpy stream for things that shuffle OUTSIDE the compiled
# program (DataLoader samplers, random_split). Seeded together with the
# device stream so `paddle.seed(k)` makes a whole run — including data
# order — reproducible regardless of what other code did to numpy's
# GLOBAL np.random state (reference contract: framework/random.py seed
# governs the generators the framework itself consumes). Entropy-seeded
# by default: without paddle.seed, each run shuffles differently, like
# the reference's unseeded DataLoader.
_host = np.random.RandomState()


def seed(s: int) -> Generator:
    """paddle.seed."""
    _global.manual_seed(s)
    _host.seed(int(s))
    return _global


def host_rng() -> np.random.RandomState:
    """The paddle.seed-governed host RNG (samplers, random_split)."""
    return _host


def host_rng_state():
    """Picklable snapshot of the host stream (data-order determinism)."""
    return _host.get_state()


def set_host_rng_state(state):
    _host.set_state(state)


def get_rng_state() -> dict:
    """Full framework RNG snapshot: the device PRNG key (eager randomness,
    dropout) AND the host stream (sampler shuffles, random_split). Both are
    needed for a resume to be bit-reproducible — restoring only the device
    key replays the model but not the data order. Stored in checkpoints'
    job_state (robustness/distributed_ft.capture_job_state)."""
    return {"device": np.asarray(_global.get_state()),
            "seed": _global.initial_seed(),
            "host": host_rng_state()}


def set_rng_state(state: dict):
    """Inverse of get_rng_state()."""
    if "seed" in state:
        _global._seed = int(state["seed"])
    _global.set_state(state["device"])
    set_host_rng_state(state["host"])


def default_generator() -> Generator:
    return _global


def next_key():
    """Fresh PRNG key: from the installed trace provider if any, else global state."""
    provider = getattr(_tls, "provider", None)
    if provider is not None:
        return provider()
    return _global.next_key()


@contextlib.contextmanager
def key_provider(fn):
    """Install a callable returning fresh (possibly traced) keys for this thread."""
    prev = getattr(_tls, "provider", None)
    _tls.provider = fn
    try:
        yield
    finally:
        _tls.provider = prev


class TracedKeyStream:
    """Deterministic key stream derived from one (traced) base key via fold_in."""

    def __init__(self, base_key):
        self.base = base_key
        self.count = 0

    def __call__(self):
        self.count += 1
        return jax.random.fold_in(self.base, self.count)


class CounterKeyStream:
    """Content-addressed key stream: ``key(identity, counter)``.

    The serving-side generalization of :class:`TracedKeyStream` — instead
    of a mutable per-trace counter, every key is a pure function of
    (stream seed, identity, counter), so the stream has NO state to lose:
    a request replayed after replica eviction, or landing in a different
    decode batch, draws bit-identical keys for the same positions. String
    identities hash through crc32 so a request id is usable directly.

    Key creation is lazy for the same reason as :class:`Generator`:
    ``jax.random.key`` must not force backend init at import time.
    """

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._base = None

    @staticmethod
    def _ident(identity) -> int:
        if isinstance(identity, str):
            import zlib

            return zlib.crc32(identity.encode("utf-8"))
        return int(identity) & 0xFFFFFFFF

    def key(self, identity, counter: int):
        """The one key for (identity, counter) — always the same one."""
        if self._base is None:
            self._base = jax.random.key(self._seed)
        return jax.random.fold_in(
            jax.random.fold_in(self._base, self._ident(identity)),
            int(counter))

    def keys(self, identities, counters):
        """Stacked typed-key array for a batch of (identity, counter)."""
        import jax.numpy as jnp

        return jnp.stack([self.key(i, c)
                          for i, c in zip(identities, counters)])


def get_cuda_rng_state():  # API-compat shims
    return [_global.get_state()]


def set_cuda_rng_state(states):
    if states:
        _global.set_state(states[0])


class RNGStatesTracker:
    """Named RNG states for TP-consistent dropout.

    Reference: meta_parallel/parallel_layers/random.py get_rng_state_tracker —
    'global' dropout differs across mp ranks, 'local' matches. Here each name is
    its own Generator seeded explicitly.
    """

    def __init__(self):
        self.states_: dict[str, Generator] = {}

    def add(self, name: str, seed_: int):
        if name in self.states_:
            raise ValueError(f"state {name!r} already exists")
        self.states_[name] = Generator(seed_)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        gen = self.states_.get(name)
        if gen is None:
            gen = self.states_[name] = Generator(0)
        with key_provider(gen.next_key):
            yield


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _rng_tracker


def model_parallel_random_seed(seed_: int, tp_rank: int = 0):
    global _rng_tracker
    _rng_tracker = RNGStatesTracker()
    _rng_tracker.add("global_seed", 100 + seed_)
    _rng_tracker.add("local_seed", 1000 + seed_ + tp_rank)
    _global.manual_seed(100 + seed_)

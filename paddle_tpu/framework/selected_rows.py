"""SelectedRows — row-sparse gradients for vocab-scale embedding tables.

Reference: paddle/pten/core/selected_rows.h:38 (rows + value tensor + height)
produced by lookup_table grad kernels and consumed by the sparse optimizer
kernels (adam/sgd "lazy mode") and the PS sparse push.

TPU-native: (rows[int32 n], values[n, dim]) jax arrays. The backward of a
vocab-[V, d] embedding lookup allocates O(batch·seq·d), never O(V·d); the
optimizer applies a segment-summed scatter update touching only the live
rows. to_dense() exists for interop but defeats the point at CTR scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class SelectedRows:
    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height: int):
        self.rows = rows          # [n] int array (may contain duplicates)
        self.values = values      # [n, ...] per-row gradient values
        self.height = int(height)  # full table row count (V)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def merge(self) -> "SelectedRows":
        """Deduplicate rows (MergeAdd, selected_rows_functor.cc): sum values
        of identical rows. O(n log n) on device."""
        rows = self.rows
        uniq, inv = jnp.unique(rows, return_inverse=True,
                               size=rows.shape[0], fill_value=-1)
        summed = jax.ops.segment_sum(self.values, inv,
                                     num_segments=rows.shape[0])
        return SelectedRows(uniq, summed, self.height)

    def to_dense(self):
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        # dense + sparse → dense (rare; e.g. tied weights used densely too)
        return jnp.asarray(other).at[self.rows].add(self.values)

    __radd__ = __add__

    def numpy(self):
        return np.asarray(self.to_dense())

    def __array__(self, dtype=None):
        d = self.numpy()
        return d.astype(dtype) if dtype is not None else d

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"rows={self.rows.shape[0]}, dim={self.values.shape[1:]})")


def apply_row_sparse(param_value, grad: SelectedRows, update_fn):
    """Apply update_fn(rows_slice, grad_values) -> new_rows_slice to only the
    touched rows of param_value. Returns the updated dense param."""
    g = grad.merge()
    valid = g.rows >= 0
    rows = jnp.where(valid, g.rows, 0)
    cur = param_value[rows]
    new = update_fn(cur, g.values)
    # scatter-ADD the delta: padding slots (row -1 → 0) contribute exactly 0,
    # so duplicate indices stay correct (scatter-set with dupes would not be)
    delta = jnp.where(valid[:, None], new - cur, 0)
    return param_value.at[rows].add(delta)

"""paddle.io — datasets and DataLoader.

Reference: python/paddle/fluid/reader.py:146 (DataLoader), fluid/dataloader/
(Dataset/IterableDataset/BatchSampler, multiprocess workers over a shared-mem
queue + C++ LoDTensorBlockingQueue).

TPU-native: the loader is a host-side prefetch pipeline feeding device puts; a
background-thread prefetcher overlaps host batch assembly with device compute
(the role the reference's blocking queue plays). num_workers>0 uses a thread
pool for sample loading — Python-level parallelism is enough to keep a TPU fed
when transforms are NumPy-bound.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Iterable, List, Optional

import numpy as np

from ..framework import random as rng_mod
from ..framework.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        # fraction form
        if all(0 < l < 1 for l in lengths):
            lengths = [int(l * total) for l in lengths]
            lengths[-1] = total - sum(lengths[:-1])
        else:
            raise ValueError("sum of lengths != dataset size")
    if generator is None:
        rng = rng_mod.host_rng()
    elif isinstance(generator, np.random.RandomState):
        rng = generator
    else:
        # framework Generator: keep ONE host stream per generator so
        # repeated splits advance it (re-seeding from initial_seed every
        # call would return identical permutations)
        rng = getattr(generator, "_host_rng", None)
        if rng is None:
            rng = np.random.RandomState(generator.initial_seed())
            generator._host_rng = rng
    perm = rng.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off : off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num = num_samples

    @property
    def num_samples(self):
        return self._num or len(self.data_source)

    def __iter__(self):
        # paddle.seed-governed host stream, NOT the global np.random: data
        # order must not depend on what unrelated code drew before us
        n = len(self.data_source)
        rng = rng_mod.host_rng()
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Random permutation over a fixed index subset (reference io sampler)."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        return iter(self.indices[i]
                    for i in rng_mod.host_rng().permutation(
                        len(self.indices)))

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(
            rng_mod.host_rng().choice(
                len(p), self.num_samples, replace=self.replacement,
                p=p).tolist()
        )

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the sample space across data-parallel ranks (reference:
    fluid/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank : self.total_size : self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    """paddle.io.DataLoader (reference: fluid/reader.py:146).

    Batches are produced on a prefetch thread (capacity=`prefetch_factor`)
    and returned as Tensors on the current device.

    num_workers > 0 startup cost: workers use the 'spawn' start method
    (fork after the JAX backend initializes is unsafe), so EACH pool
    creation re-imports the framework in every worker (~10s+). Steady-state
    throughput then matches in-process loading. Amortize it with
    `persistent_workers=True` (one pool for the loader's lifetime) and/or
    `PADDLE_DATALOADER_START_METHOD=forkserver` (imports once in a fork
    server; safe as long as worker code doesn't rely on inheriting a
    live JAX backend — workers pin themselves to CPU anyway).
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(2, int(prefetch_factor))
        # device-side prefetch (reference use_double_buffer): producer
        # thread issues the device puts so transfer overlaps compute
        self._buffer_reader = bool(use_buffer_reader)
        self.return_list = return_list
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = bool(persistent_workers)
        self._persistent_pool = None
        self._epoch = 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size or 1, drop_last=drop_last
            )
        # num_workers > 0 → real worker PROCESSES (io/worker.py, the
        # reference's _DataLoaderIterMultiProcess); the thread prefetcher
        # below only overlaps collate with compute for num_workers == 0
        self._pool = None
        # native prefetch buffer (C++ blocking queue — the
        # LoDTensorBlockingQueue analog); opt-in via flag — for in-process
        # thread handoff the Python queue is zero-copy and faster, the native
        # queue exists for serialized/cross-process transport
        from ..framework.flags import flag as _flag

        self._use_native_queue = (bool(use_shared_memory)
                                  and self.num_workers > 0
                                  and bool(_flag(
                                      "FLAGS_use_native_dataloader_queue")))
        if self._use_native_queue:
            try:
                from ..core.table import BlockingQueue  # noqa: F401
                from ..core import load_library

                load_library()
            except Exception:
                self._use_native_queue = False

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("length of IterableDataset DataLoader is unknown")
        return len(self.batch_sampler)

    def _fetch(self, indices):
        if self._pool is not None:
            samples = list(self._pool.map(self.dataset.__getitem__, indices))
        else:
            samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        else:
            for indices in self.batch_sampler:
                yield self._fetch(indices)

    def _device_stage(self, host_iter):
        """use_buffer_reader (reference use_double_buffer,
        reader.py:442-478): a parent-side thread applies the device puts
        over `host_iter`, keeping up to 2 device-resident batches queued —
        the next batch's host->device transfer is in flight while the
        consumer's current step computes (jax transfers are async)."""
        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=2)
        sentinel = object()
        stop = threading.Event()
        err: List[BaseException] = []

        def stager():
            try:
                for batch in host_iter:
                    staged = _to_tensors(batch)
                    while not stop.is_set():
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue_mod.Full:
                            continue
                    else:
                        return
            except BaseException as e:
                err.append(e)
            finally:
                try:
                    q.put(sentinel, timeout=5)
                except queue_mod.Full:
                    pass

        t = threading.Thread(target=stager, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue_mod.Empty:
                pass
            t.join(timeout=5)

    def _iter_native(self):
        from ..core.table import BlockingQueue

        q = BlockingQueue(self.prefetch_factor)
        err: List[BaseException] = []

        def producer():
            try:
                for batch in self._batches():
                    while True:
                        try:
                            q.push(batch, timeout_ms=100)
                            break
                        except TimeoutError:
                            if q.closed:
                                return
                        except RuntimeError:  # closed by consumer
                            return
            except BaseException as e:
                err.append(e)
            finally:
                q.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.pop()
                if item is None:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            q.close()
            t.join(timeout=5)

    def _iter_multiprocess(self, transform):
        from .worker import MPIterableIterator, MPMapIterator, _WorkerPool

        if self._iterable_mode:
            pool = _WorkerPool(self)
            it = MPIterableIterator(self, pool, transform)
        else:
            if self.persistent_workers:
                if self._persistent_pool is None or \
                        self._persistent_pool.closed:
                    self._persistent_pool = _WorkerPool(self)
                pool = self._persistent_pool
            else:
                pool = _WorkerPool(self)
            it = MPMapIterator(self, pool, self._epoch, transform)
            self._epoch += 1
        try:
            yield from it
        finally:
            it.close()

    def __del__(self):
        pool = getattr(self, "_persistent_pool", None)
        if pool is not None:
            pool.shutdown()

    def __iter__(self):
        # opt-in native C++ queue path first (in-process, flag-gated), then
        # real multiprocess workers, then the thread prefetcher. Every path
        # honors use_buffer_reader: batches cross the pipeline as HOST
        # arrays and the device put runs either on the _device_stage
        # thread (flag on — transfer overlaps compute) or at consume time
        # (flag off).
        host_iter = None
        if self._use_native_queue:
            host_iter = self._iter_native()
        elif self.num_workers > 0:
            host_iter = self._iter_multiprocess(lambda b: b)
        if host_iter is not None:
            if self._buffer_reader:
                yield from self._device_stage(host_iter)
            else:
                for b in host_iter:
                    yield _to_tensors(b)
            return
        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=self.prefetch_factor)
        sentinel = object()
        stop = threading.Event()
        err: List[BaseException] = []

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        # use_buffer_reader (reference: use_double_buffer,
        # reader.py:442-478): stage the device put on the PRODUCER thread,
        # so the next batch's host->device transfer is already in flight
        # while the consumer's current step computes — jax dispatches
        # transfers asynchronously, the queue holds at most
        # prefetch_factor device-resident batches (the reference's double
        # buffer holds 2). With the flag off, batches cross the queue as
        # host arrays and the put happens at consume time.
        stage = _to_tensors if self._buffer_reader else (lambda b: b)
        finish = (lambda b: b) if self._buffer_reader else _to_tensors

        def producer():
            try:
                for batch in self._batches():
                    if not _put(stage(batch)):
                        return  # consumer abandoned the iterator
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                _put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield finish(item)
        finally:
            # unblock + reap the producer even if iteration stopped early
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue_mod.Empty:
                pass
            t.join(timeout=5)


def _to_tensors(batch):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return [_to_tensors(b) for b in batch]
    if isinstance(batch, dict):
        return {k: _to_tensors(v) for k, v in batch.items()}
    return batch


from .worker import WorkerInfo, get_worker_info  # noqa: F401,E402

"""Multiprocess DataLoader workers.

Reference: fluid/reader.py _DataLoaderIterMultiProcess + the C++ shared-mem
queue (paddle/fluid/imperative/data_loader.cc): worker PROCESSES fetch and
collate samples so a GIL-bound __getitem__ cannot starve the device input
pipeline; batches return over a pickle ring (mp.Queue) and are re-ordered by
batch index so iteration order is deterministic regardless of worker timing.

TPU framing: the consumer is an ICI-fed chip expecting a steady HBM feed; the
parent process only deserializes and device_puts, all decode work lives in
the workers. Workers use the 'spawn' start method — fork after the JAX
backend initializes is unsafe (runtime threads don't survive fork).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import queue as queue_mod
import traceback
from typing import Optional

_worker_info = None


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed=0):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers})")


def get_worker_info() -> Optional[WorkerInfo]:
    """paddle.io.get_worker_info — non-None only inside a worker process."""
    return _worker_info


def _worker_loop(dataset, index_q, result_q, collate_fn, worker_id,
                 num_workers, init_fn, iterable, batch_size, drop_last):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    # keep workers off the accelerator: data decode is host work
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        if init_fn is not None:
            init_fn(worker_id)
        if iterable:
            it = iter(dataset)
            while True:
                chunk = list(itertools.islice(it, batch_size))
                if not chunk or (len(chunk) < batch_size and drop_last):
                    break
                result_q.put(("data", None, collate_fn(chunk), None))
            result_q.put(("done", worker_id, None, None))
        else:
            while True:
                task = index_q.get()
                if task is None:
                    break
                epoch, bidx, indices = task
                try:
                    batch = collate_fn([dataset[i] for i in indices])
                    result_q.put(("data", (epoch, bidx), batch, None))
                except Exception:
                    result_q.put(("data", (epoch, bidx), None,
                                  traceback.format_exc()))
    except KeyboardInterrupt:
        pass
    except Exception:
        try:
            result_q.put(("fatal", worker_id, None, traceback.format_exc()))
        except (OSError, ValueError, BrokenPipeError):
            # the parent (and its queue) are already gone — there is no
            # channel left to report on; narrow so a genuinely different
            # fault in the put path still surfaces (rule C003)
            pass


class _WorkerPool:
    """Spawned worker processes + index/result queues (one pool per loader
    when persistent_workers, else per epoch)."""

    def __init__(self, loader):
        ctx = mp.get_context(
            os.environ.get("PADDLE_DATALOADER_START_METHOD", "spawn"))
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.num_workers = loader.num_workers
        self.procs = []
        for wid in range(loader.num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.index_q, self.result_q,
                      loader.collate_fn, wid, loader.num_workers,
                      loader.worker_init_fn, loader._iterable_mode,
                      getattr(loader, "batch_size", 1),
                      getattr(loader, "drop_last", False)),
                daemon=True)
            p.start()
            self.procs.append(p)
        self.closed = False

    def shutdown(self):
        if self.closed:
            return
        self.closed = True
        for _ in self.procs:
            try:
                self.index_q.put(None)
            except (OSError, ValueError, BrokenPipeError):
                # a worker that crashed mid-epoch can leave the queue's
                # pipe closed; shutdown still proceeds to terminate() below
                pass
        for p in self.procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()


class MPMapIterator:
    """Ordered multiprocess iteration over a map-style dataset."""

    def __init__(self, loader, pool: _WorkerPool, epoch: int, to_tensors):
        self.loader = loader
        self.pool = pool
        self.epoch = epoch
        self.to_tensors = to_tensors
        self.batches = list(loader.batch_sampler)
        self.total = len(self.batches)
        self.dispatched = 0
        self.yielded = 0
        self.buffer = {}
        self.timeout = loader.timeout or 120
        # prime the pipeline
        depth = max(2, loader.prefetch_factor) * pool.num_workers
        for _ in range(min(depth, self.total)):
            self._dispatch()

    def _dispatch(self):
        if self.dispatched < self.total:
            self.pool.index_q.put(
                (self.epoch, self.dispatched, self.batches[self.dispatched]))
            self.dispatched += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self.yielded >= self.total:
            raise StopIteration
        while self.yielded not in self.buffer:
            try:
                kind, tag, batch, err = self.pool.result_q.get(
                    timeout=self.timeout)
            except queue_mod.Empty:
                dead = [p.pid for p in self.pool.procs if not p.is_alive()]
                self.pool.shutdown()
                if dead:
                    raise RuntimeError(
                        f"DataLoader worker process(es) {dead} died "
                        f"without reporting an error — commonly the "
                        f"dataset class is not importable in a spawned "
                        f"worker (defined in a REPL/heredoc __main__), or "
                        f"the worker was OOM-killed")
                raise RuntimeError(
                    f"DataLoader worker timed out after {self.timeout}s "
                    f"with workers still alive — a slow __getitem__, or "
                    f"first-batch worker startup (spawned workers re-import "
                    f"the framework; see DataLoader docstring: "
                    f"persistent_workers=True amortizes it across epochs, "
                    f"PADDLE_DATALOADER_START_METHOD=forkserver halves it)")
            if kind == "fatal" or (err is not None):
                self.pool.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            epoch, bidx = tag
            if epoch != self.epoch:
                continue  # stale result from an abandoned epoch
            self.buffer[bidx] = batch
        out = self.buffer.pop(self.yielded)
        self.yielded += 1
        self._dispatch()
        return self.to_tensors(out)

    def close(self):
        if not self.loader.persistent_workers:
            self.pool.shutdown()


class MPIterableIterator:
    """Multiprocess iteration over an IterableDataset: every worker runs its
    own iterator (shard via get_worker_info, reference semantics); batches
    arrive unordered."""

    def __init__(self, loader, pool: _WorkerPool, to_tensors):
        self.pool = pool
        self.to_tensors = to_tensors
        self.done = 0
        self.timeout = loader.timeout or 120

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self.done >= self.pool.num_workers:
                self.pool.shutdown()
                raise StopIteration
            try:
                kind, tag, batch, err = self.pool.result_q.get(
                    timeout=self.timeout)
            except queue_mod.Empty:
                self.pool.shutdown()
                raise RuntimeError(
                    f"DataLoader worker timed out after {self.timeout}s")
            if kind == "fatal" or err is not None:
                self.pool.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            if kind == "done":
                self.done += 1
                continue
            return self.to_tensors(batch)

    def close(self):
        self.pool.shutdown()

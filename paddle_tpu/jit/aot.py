"""Ahead-of-time compilation of TrainSteps for described TPU topologies.

Reference analog: the auto-parallel cost model + cluster description
(python/paddle/distributed/auto_parallel/cost_model.py, cluster.py) — the
reference predicts a distributed program's step time and memory with a
hand-written simulator because compiling for a CUDA cluster it doesn't
have is impossible. On TPU the roles invert: jax.experimental.topologies
describes any v5e/v4 slice, XLA-TPU compiles the REAL train step for it
(no hardware, no execution), and the compiler's own cost/memory analysis
replaces the simulator. Used by distributed.auto_parallel.planner (mesh
search) and tools/{gpt13b,hybrid}_aot_tpu.py (feasibility artifacts).

The one rule: topology devices are described, not addressable — build
models/optimizers/inputs with NO mesh active (arrays stay on CPU), then
set the topology mesh, then compile abstractly here.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

__all__ = ["aot_compile_step", "topology_mesh", "estimate_step_seconds"]

# v5e per-chip peaks (shared with bench/tools MFU math — one source)
V5E_PEAK_BF16_FLOPS = 197e12
V5E_HBM_BYTES_PER_S = 819e9


def estimate_step_seconds(cost: Dict,
                          peak_flops: float = V5E_PEAK_BF16_FLOPS,
                          hbm_bw: float = V5E_HBM_BYTES_PER_S,
                          ) -> Optional[Dict]:
    """Best available per-device step-time estimate from a cost dict.

    XLA-TPU's `optimal_seconds` is authoritative when positive, but goes
    negative (an unknown-cost sentinel accumulating) on larger programs
    with collectives. Fall back to a roofline bound from the compiler's
    own flops / bytes-accessed counters: max(compute-bound, HBM-bound).
    Returns {"seconds", "signal"} with signal "compiler" | "roofline",
    or None when neither is available. The roofline ignores ICI time, so
    it is a LOWER bound — fine for ranking same-model candidates, not an
    absolute throughput claim.
    """
    opt_s = cost.get("optimal_seconds")
    if opt_s is not None and opt_s > 0:
        return {"seconds": float(opt_s), "signal": "compiler"}
    fl, by = cost.get("flops"), cost.get("bytes_accessed")
    if fl and fl > 0:
        sec = fl / peak_flops
        if by and by > 0:
            sec = max(sec, by / hbm_bw)
        return {"seconds": float(sec), "signal": "roofline"}
    return None


def topology_mesh(name: str, shape_map: Dict[str, int]):
    """Mesh over a described TPU topology, e.g. ("v5e:2x4",
    {"data": 2, "model": 4}). Device order is raw topology order — fine
    for compile-time cost/memory analysis, which is order-invariant."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    topo = topologies.get_topology_desc(platform="tpu", topology_name=name)
    axes = tuple(shape_map)
    degs = tuple(shape_map[a] for a in axes)
    n = 1
    for d in degs:
        n *= d
    if len(topo.devices) != n:
        raise ValueError(f"{name} has {len(topo.devices)} chips, "
                         f"mesh {shape_map} wants {n}")
    return Mesh(np.asarray(topo.devices).reshape(degs), axes)


def compile_pallas_flash_for_tpu(shape=(8, 1024, 12, 64), block_size=512,
                                 topology: str = "v5e:2x4",
                                 grad: bool = True) -> float:
    """Compile the pallas flash-attention kernel (Mosaic, not interpret)
    for one chip of a described TPU topology; returns compile seconds.
    Shared by tools/hybrid_aot_tpu.py and tests/test_tpu_aot.py so the
    validation recipe can't drift."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..framework.target import force_target
    from ..ops.flash_attention import flash_attention_val

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology)
    mesh1 = Mesh(np.asarray(topo.devices[:1]).reshape(1), ("x",))
    sh = NamedSharding(mesh1, P())
    q = jax.ShapeDtypeStruct(tuple(shape), jnp.bfloat16, sharding=sh)

    if grad:
        fn = jax.grad(lambda a, b, c: jnp.sum(flash_attention_val(
            a, b, c, block_size=block_size).astype(jnp.float32)),
            argnums=(0, 1, 2))
        jitted = jax.jit(fn, in_shardings=(sh, sh, sh))
    else:
        jitted = jax.jit(
            lambda a, b, c: flash_attention_val(a, b, c,
                                                block_size=block_size),
            in_shardings=(sh, sh, sh), out_shardings=sh)
    # force_target: mesh1 is a raw jax mesh, not the framework's ambient
    # mesh, so the pallas interpret gate needs the explicit pin
    with force_target("tpu"):
        t0 = time.time()
        jitted.lower(q, q, q).compile()
    return round(time.time() - t0, 1)


def aot_compile_step(step, inputs, labels, want_cost: bool = False) -> Dict:
    """Abstractly lower + compile a TrainStep for the ACTIVE mesh, exactly
    the way TrainStep.__call__ would run it (same pure function, same
    in/out shardings), but with ShapeDtypeStruct arguments — nothing
    executes, so the mesh may live on a described topology.

    Returns compile_seconds + XLA memory analysis (argument/output/temp/
    alias/peak bytes, per device); with want_cost also the compiler's
    cost analysis (optimal_seconds = estimated step time, flops).
    """
    import jax

    from . import tree_to_vals

    fm = step.fm
    in_vals = tree_to_vals(tuple(inputs))
    lbl_vals = tree_to_vals(tuple(labels))
    opt = step.optimizer
    train_params = [p for p, m in zip(fm.params, fm.trainable_mask) if m]
    step._slots = [opt._init_slots(p._value) for p in train_params]
    pure = step._build(("aot",))
    jitted = step._compile(pure, step._slots, in_vals, lbl_vals)

    SDS = jax.ShapeDtypeStruct

    def sds(v):
        return SDS(v.shape, v.dtype)

    pvals = fm.param_values()
    train_p = [sds(v) for v, m in zip(pvals, fm.trainable_mask) if m]
    frozen_p = [sds(v) for v, m in zip(pvals, fm.trainable_mask) if not m]
    bvals = [sds(v) for v in fm.buffer_values()]
    slots = jax.tree_util.tree_map(sds, step._slots)
    key = jax.random.key(0)
    lowered = jitted.lower(
        train_p, frozen_p, bvals, slots, sds(key),
        SDS((), "float32"),
        jax.tree_util.tree_map(sds, in_vals),
        jax.tree_util.tree_map(sds, lbl_vals))
    t0 = time.time()
    compiled = lowered.compile()
    out: Dict = {"compile_seconds": round(time.time() - t0, 1)}
    mem = compiled.memory_analysis()
    if mem is not None:
        out.update(
            argument_bytes=int(mem.argument_size_in_bytes),
            output_bytes=int(mem.output_size_in_bytes),
            temp_bytes=int(mem.temp_size_in_bytes),
            alias_bytes=int(mem.alias_size_in_bytes))
        out["peak_hbm_bytes"] = (out["argument_bytes"] + out["temp_bytes"]
                                 + out["output_bytes"] - out["alias_bytes"])
    if want_cost:
        out.update(cost_counters(compiled))
    return out


def cost_counters(compiled) -> Dict:
    """Raw compiler cost counters from a compiled executable, normalized
    to {optimal_seconds, flops, bytes_accessed} (keys present only when
    the backend reports them). estimate_step_seconds decides how far to
    trust them. Shared by aot_compile_step and models.gpt
    .gpt_hbm_estimate so the key mapping can't drift."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # backends without cost analysis
        ca = None
    out: Dict = {}
    if isinstance(ca, dict):
        for src, dst in (("optimal_seconds", "optimal_seconds"),
                         ("flops", "flops"),
                         ("bytes accessed", "bytes_accessed")):
            if ca.get(src) is not None:
                out[dst] = float(ca[src])
    return out

"""Functional bridge: run a mutable Layer as a pure jax function.

This is the TPU-native replacement for the reference's dygraph→static machinery
(python/paddle/fluid/dygraph/dygraph_to_static/ + run_program_op): instead of
AST-transforming Python into a ProgramDesc, we *trace* the layer's forward with
tracer values swapped into its Parameters/buffers, yielding a pure function

    (param_vals, buffer_vals, rng_key, *input_vals) -> (outputs, new_buffer_vals)

that jax.jit/pjit compile to a single XLA program. Buffer mutation (BatchNorm
running stats) is captured because mutation rebinds Tensor._value, which holds
a tracer during tracing — the functional state threading the reference does
with Scope side effects falls out of the design for free.
"""
from __future__ import annotations

from typing import Any, List, Sequence

import jax

from ..framework import autograd, random as rng_mod
from ..framework.tensor import Tensor


def tree_to_vals(tree):
    """Extract raw jax values from a pytree containing Tensors."""
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x,
        tree,
        is_leaf=lambda x: isinstance(x, Tensor),
    )


def vals_to_tensors(tree, stop_gradient=True):
    def wrap(v):
        t = Tensor(v, _internal=True)
        t.stop_gradient = stop_gradient
        return t

    return jax.tree_util.tree_map(wrap, tree)


class FunctionalModule:
    """Snapshot of a Layer's parameter/buffer structure + pure call."""

    def __init__(self, layer):
        self.layer = layer
        self.param_names: List[str] = []
        self.params: List[Tensor] = []
        for n, p in layer.named_parameters():
            self.param_names.append(n)
            self.params.append(p)
        self.buffer_names: List[str] = []
        self.buffers: List[Tensor] = []
        for n, b in layer.named_buffers():
            self.buffer_names.append(n)
            self.buffers.append(b)
        self.trainable_mask = [not p.stop_gradient for p in self.params]

    def param_values(self):
        return [p._value for p in self.params]

    def split_values(self, pvals):
        """(trainable, frozen) in mask order."""
        train = [v for v, m in zip(pvals, self.trainable_mask) if m]
        frozen = [v for v, m in zip(pvals, self.trainable_mask) if not m]
        return train, frozen

    def merge_values(self, train, frozen):
        """Inverse of split_values — the ONE ordering contract shared by
        TrainStep and external grad engines (1F1B)."""
        out, ti, fi = [], 0, 0
        for m in self.trainable_mask:
            if m:
                out.append(train[ti])
                ti += 1
            else:
                out.append(frozen[fi])
                fi += 1
        return out

    def buffer_values(self):
        return [b._value for b in self.buffers]

    def bind_params(self, pvals):
        for p, v in zip(self.params, pvals):
            p._value = v

    def bind_buffers(self, bvals):
        for b, v in zip(self.buffers, bvals):
            b._value = v

    def call(self, pvals, bvals, key, args, kwargs=None, training=None, fn=None):
        """Pure functional call: returns (output value tree, new buffer vals).

        Safe to invoke under jax tracing: all mutation is confined to the
        swapped-in values and restored afterwards.
        """
        kwargs = kwargs or {}
        old_p = [p._value for p in self.params]
        old_b = [b._value for b in self.buffers]
        old_training = self.layer.training
        try:
            self.bind_params(pvals)
            self.bind_buffers(bvals)
            if training is not None:
                self.layer.train() if training else self.layer.eval()
            targs = vals_to_tensors(args)
            tkw = vals_to_tensors(kwargs)
            stream = rng_mod.TracedKeyStream(key)
            with rng_mod.key_provider(stream), autograd.no_grad():
                if fn is not None:
                    out = fn(self.layer, *targs, **tkw)
                else:
                    out = self.layer(*targs, **tkw)
            new_bvals = [b._value for b in self.buffers]
            return tree_to_vals(out), new_bvals
        finally:
            self.bind_params(old_p)
            self.bind_buffers(old_b)
            if training is not None:
                self.layer.train() if old_training else self.layer.eval()

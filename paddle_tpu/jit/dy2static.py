"""dygraph→static AST transforms for data-dependent Python control flow.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ (~20k LoC of
*_transformer.py): `if/while/for` over Tensors rewrite to cond/while ops so
one compiled program covers every branch. TPU-native targets are the XLA
structured-control-flow primitives instead of ProgramDesc blocks:

    if <tensor>:  → jax.lax.cond        (convert_ifelse below)
    while <tensor>: → jax.lax.while_loop (convert_while)
    for i in range(<tensor>): → rewritten to an equivalent while

The decision is made at RUNTIME exactly like the reference's convert_ifelse
(convert_operators.py): a Python-bool condition keeps plain Python control
flow (no tracing overhead, no shape constraints); only a traced/Tensor
condition enters the lax primitive. Functions where transformation cannot
apply (no source, closures over free variables whose cells we cannot rebind,
`break`/`continue`/`return` inside a converted block) fall back to the
trace-only path, which bakes the traced branch — the pre-transform behavior.

Supported subset: conditions/carried state must be tensors or numerics, the
carried variables must be bound before the statement, and both branches must
produce matching shapes/dtypes (an XLA requirement the reference shares for
its cond blocks).
"""
from __future__ import annotations

import ast
import inspect
import textwrap
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

_HELPER = "_pt_dy2st"


# ---------------------------------------------------------------------------
# runtime converters
# ---------------------------------------------------------------------------

class _Undefined:
    """Placeholder for a name unbound before a converted statement
    (reference: dygraph_to_static UndefinedVar). Using it is an error."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined before converted control flow>"


UNDEF = _Undefined()


def get_args(thunks):
    """Evaluate carried-name thunks; unbound names become UNDEF."""
    out = []
    for t in thunks:
        try:
            out.append(t())
        except (NameError, UnboundLocalError):
            out.append(UNDEF)
    return tuple(out)


def _unwrap(v):
    from ..framework.tensor import Tensor

    return v._value if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_unwrap(v), jax.core.Tracer)


def _wrap_state(vals, protos):
    from ..framework.tensor import Tensor

    out = []
    for v, p in zip(vals, protos):
        if isinstance(p, Tensor):
            t = Tensor(v, _internal=True)
            t.stop_gradient = p.stop_gradient
            out.append(t)
        else:
            out.append(v)
    return tuple(out)


def _unwrap_state(state):
    return tuple(jnp.asarray(_unwrap(v)) for v in state)


def _numeric(v):
    if v is UNDEF:
        return False
    u = _unwrap(v)
    return isinstance(u, (int, float, bool, complex, np.ndarray, np.number,
                          jax.Array, jax.core.Tracer))


def convert_ifelse(pred, true_fn, false_fn, state):
    """if/else with tensor predicate → lax.cond; python predicate → python."""
    p = _unwrap(pred)
    if _is_traced(p) or any(_is_traced(s) for s in state):
        from ..framework.tensor import Tensor

        protos = tuple(state)
        # UNDEF / non-numeric entries ride along statically (both branches
        # must overwrite an UNDEF for its output to be legal)
        is_op = [_numeric(s) for s in state]
        operands = tuple(jnp.asarray(_unwrap(s))
                         for s, m in zip(state, is_op) if m)

        def assemble(vals):
            it = iter(vals)
            full = []
            for proto, m in zip(protos, is_op):
                full.append(_wrap_state((next(it),), (proto,))[0] if m
                            else proto)
            return tuple(full)

        def outs_of(branch_fn, vals):
            out = branch_fn(*assemble(vals))
            bad = [i for i, o in enumerate(out) if not _numeric(o)]
            if bad:
                raise ValueError(
                    "under a tensor-`if`, every carried variable must be a "
                    "tensor/number in BOTH branches (a variable assigned in "
                    "only one branch cannot leave a traced cond)")
            return tuple(jnp.asarray(_unwrap(o)) for o in out)

        pred_val = jnp.asarray(p).astype(bool).reshape(())
        out = jax.lax.cond(pred_val,
                           lambda vs: outs_of(true_fn, vs),
                           lambda vs: outs_of(false_fn, vs), operands)
        wrapped = []
        for o, proto in zip(out, protos):
            if isinstance(proto, Tensor) or proto is UNDEF or not _numeric(
                    proto):
                t = Tensor(o, _internal=True)
                if isinstance(proto, Tensor):
                    t.stop_gradient = proto.stop_gradient
                wrapped.append(t)
            else:
                wrapped.append(o)
        return tuple(wrapped)
    truthy = bool(np.asarray(p)) if hasattr(p, "shape") or hasattr(
        p, "__array__") else bool(p)
    return tuple(true_fn(*state) if truthy else false_fn(*state))


def convert_while(cond_fn, body_fn, state):
    """while with tensor condition → lax.while_loop."""
    c0 = _unwrap(cond_fn(*state))
    if _is_traced(c0) or any(_is_traced(s) for s in state):
        if any(s is UNDEF for s in state):
            raise ValueError(
                "a variable assigned under a tensor-`while` must be bound "
                "before the loop (lax.while_loop needs a concrete carry)")
        protos = tuple(state)

        def cond(vs):
            r = _unwrap(cond_fn(*_wrap_state(vs, protos)))
            return jnp.asarray(r).astype(bool).reshape(())

        def body(vs):
            return _unwrap_state(body_fn(*_wrap_state(vs, protos)))

        out = jax.lax.while_loop(cond, body, _unwrap_state(state))
        return _wrap_state(out, protos)
    while bool(np.asarray(_unwrap(cond_fn(*state)))):
        state = tuple(body_fn(*state))
    return tuple(state)


# ---------------------------------------------------------------------------
# AST transformer
# ---------------------------------------------------------------------------

class _BreaksScan(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True

    def visit_Return(self, node):
        self.found = True

    # don't descend into nested loops for break/continue... still flag:
    # conservative (a nested loop's own break is fine, but flagging it only
    # costs us a fallback, never correctness)
    def visit_FunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass


def _has_jump(stmts: List[ast.stmt]) -> bool:
    s = _BreaksScan()
    for st in stmts:
        s.visit(st)
    return s.found


class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_For(self, node):
        if isinstance(node.target, ast.Name):
            self.names.add(node.target.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts: List[ast.stmt]):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return sorted(v.names)


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _ret_tuple(names):
    return ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in names], ctx=ast.Load()))


def _state_expr(names):
    """get_args((lambda: a, lambda: b, ...)) — tolerates unbound names."""
    thunks = [ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                           kw_defaults=[], defaults=[]),
        body=_name(n)) for n in names]
    return ast.Call(
        func=ast.Attribute(value=_name(_HELPER), attr="get_args",
                           ctx=ast.Load()),
        args=[ast.Tuple(elts=thunks, ctx=ast.Load())], keywords=[])


class ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While/For(range) statements into converter calls."""

    def __init__(self):
        self.count = 0
        self.changed = False

    def _fndef(self, name, params, body):
        return ast.FunctionDef(
            name=name,
            args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=p)
                                                     for p in params],
                               kwonlyargs=[], kw_defaults=[], defaults=[]),
            body=body, decorator_list=[], type_params=[])

    def visit_If(self, node):
        self.generic_visit(node)
        body, orelse = node.body, node.orelse or [ast.Pass()]
        if _has_jump(body) or _has_jump(orelse):
            return node  # unsupported jump: leave python semantics
        carried = sorted(set(_assigned(body)) | set(_assigned(orelse)))
        self.count += 1
        self.changed = True
        k = self.count
        tname, fname = f"__pt_true_{k}", f"__pt_false_{k}"
        tbody = list(node.body) + [_ret_tuple(carried)]
        fbody = list(node.orelse) + [_ret_tuple(carried)]
        call = ast.Call(
            func=ast.Attribute(value=_name(_HELPER), attr="convert_ifelse",
                               ctx=ast.Load()),
            args=[node.test, _name(tname), _name(fname),
                  _state_expr(carried)],
            keywords=[])
        if carried:
            assign = ast.Assign(
                targets=[ast.Tuple(elts=[_name(n, ast.Store())
                                         for n in carried],
                                   ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [self._fndef(tname, carried, tbody),
                self._fndef(fname, carried, fbody), assign]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_jump(node.body):
            return node
        carried = _assigned(node.body)
        if not carried:
            return node
        self.count += 1
        self.changed = True
        k = self.count
        cname, bname = f"__pt_cond_{k}", f"__pt_body_{k}"
        cbody = [ast.Return(value=node.test)]
        bbody = list(node.body) + [_ret_tuple(carried)]
        call = ast.Call(
            func=ast.Attribute(value=_name(_HELPER), attr="convert_while",
                               ctx=ast.Load()),
            args=[_name(cname), _name(bname), _state_expr(carried)],
            keywords=[])
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_name(n, ast.Store()) for n in carried],
                               ctx=ast.Store())],
            value=call)
        return [self._fndef(cname, carried, cbody),
                self._fndef(bname, carried, bbody), assign]

    def visit_For(self, node):
        self.generic_visit(node)
        # only `for <name> in range(...)` rewrites (reference: for→while);
        # other iterables stay python (trace-time unroll)
        if (node.orelse or _has_jump(node.body)
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or not (1 <= len(node.iter.args) <= 2)):
            return node
        i = node.target.id
        if len(node.iter.args) == 1:
            start, stop = ast.Constant(value=0), node.iter.args[0]
        else:
            start, stop = node.iter.args
        init = ast.Assign(targets=[_name(i, ast.Store())], value=start)
        test = ast.Compare(left=_name(i), ops=[ast.Lt()], comparators=[stop])
        inc = ast.AugAssign(target=_name(i, ast.Store()), op=ast.Add(),
                            value=ast.Constant(value=1))
        wh = ast.While(test=test, body=list(node.body) + [inc], orelse=[])
        out = [init] + self.visit_While(wh)
        return out if isinstance(out, list) else [init, out]


def transform_function(fn):
    """Return a control-flow-converted version of fn, or fn unchanged if the
    transform cannot apply (the trace-only fallback)."""
    if getattr(fn, "_not_to_static", False):
        return fn
    inner = getattr(fn, "__func__", fn)
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    tr = ControlFlowTransformer()
    tr.visit(fdef)
    if not tr.changed:
        return fn
    if inner.__closure__:
        # rebinding free-variable cells across exec is fragile; trace-only
        return fn
    ast.fix_missing_locations(tree)
    ns = dict(inner.__globals__)
    from . import dy2static as _mod

    ns[_HELPER] = _mod
    try:
        code = compile(tree, f"<dy2static:{inner.__qualname__}>", "exec")
        exec(code, ns)
        new = ns[fdef.name]
    except Exception:
        return fn
    new.__defaults__ = inner.__defaults__
    new.__kwdefaults__ = inner.__kwdefaults__
    new.__doc__ = inner.__doc__
    new.__dy2static_source__ = ast.unparse(tree)
    if hasattr(fn, "__self__"):
        return new.__get__(fn.__self__)
    return new

"""Persistent compiled-artifact cache (ISSUE 19, ROADMAP item 5).

Compile latency was the repo's last unmanaged failure mode: the serving
watchdog had to be sized above cold-compile time (PR 14), `compile_grace`
state plumbing band-aided the same liability (PR 17), and the bench had
to strip the XLA compilation cache across forced device counts because
sharing one directory between worlds aborted glibc (PR 15). This module
is the root fix — serialized executables with a validate-then-adopt
cache discipline, keyed exactly like the PR-13 kernel tune cache:

    (program_fingerprint, shape_bucket, dtype, device_kind, world)

``world`` and ``device_kind`` in the key are what make cross-device-count
sharing safe: two processes with different forced device counts can point
at the SAME cache root and never observe each other's entries (the PR-15
abort becomes unrepresentable; ``compilation_cache_subdir`` applies the
same keying to XLA's own persistent cache directory).

Capability: serialization rides ``jax.export`` — a LAZY submodule on the
jaxes this repo supports (``hasattr(jax, "export")`` is False until
``from jax import export`` runs, the root cause of a 19-test skip set
that over-approximated the missing capability). :func:`export_supported`
probes ONCE by importing it; where the probe fails the cache degrades to
a documented in-process warm path (``store``/``lookup`` still work, the
artifacts just don't survive the process) and never crashes.

Validation discipline (the PR-13 ``TuneCache`` shape, upgraded to binary
payloads): every entry carries a content digest plus the producing
jax/jaxlib version. A corrupt, torn (``FaultyFS`` partial write),
version-drifted, or key-mismatched entry is discarded LOUDLY —
``warnings.warn`` + the ``artifact_cache_total{event=discard}`` counter —
and the caller falls back to recompiling; a poisoned entry can never
poison the process. Writes are atomic (tmp + fsync + rename through a
``LocalFS`` seam) so a crash mid-write leaves either the old entry or a
``.tmp`` orphan the loader never reads.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import warnings
from typing import Any, Dict, Optional

from ..observability.metrics import get_registry as _get_registry

__all__ = [
    "CACHE_VERSION", "export_supported", "require_export", "producer_id",
    "cache_key", "ArtifactCache", "export_compiled",
    "compilation_cache_subdir",
]

CACHE_VERSION = 1

_m_events = _get_registry().counter(
    "artifact_cache_total",
    "persistent compiled-artifact cache events",
    labels=("event",))

# memoized probe result; None = not probed yet
_EXPORT_MOD: Any = None
_EXPORT_PROBED = False


def export_supported() -> bool:
    """True iff this jax can serialize/deserialize compiled programs.

    Probes ONCE per process by actually importing ``jax.export`` (a lazy
    submodule — ``hasattr(jax, "export")`` is False before the import and
    was therefore a false-negative capability gate) and checking the
    serialize/deserialize surface. Never raises.
    """
    global _EXPORT_MOD, _EXPORT_PROBED
    if _EXPORT_PROBED:
        return _EXPORT_MOD is not None
    _EXPORT_PROBED = True
    try:
        from jax import export as _export  # noqa: PLC0415

        if (callable(getattr(_export, "export", None))
                and callable(getattr(_export, "deserialize", None))):
            _EXPORT_MOD = _export
    except Exception:
        _EXPORT_MOD = None
    return _EXPORT_MOD is not None


def _export_mod():
    if not export_supported():
        raise RuntimeError(
            "jax.export unavailable in this environment "
            "(artifact_cache.export_supported() is False) — callers must "
            "stay on the in-process warm path")
    return _EXPORT_MOD


def require_export():
    """The ``jax.export`` module, via the memoized probe. The ONE way the
    repo reaches the submodule: it is lazy on supported jaxes, so
    ``jax.export.X`` attribute access fails on a bare ``import jax`` —
    the bug class behind the historical 19-test skip set. Raises the
    probe-naming RuntimeError where unsupported."""
    return _export_mod()


def producer_id() -> str:
    """Identity of the producing toolchain; part of every entry. A cache
    entry from a different jax/jaxlib may deserialize into garbage (or a
    different calling convention), so drift discards the entry."""
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover - jaxlib rides with jax
        jl = "?"
    return f"jax-{jax.__version__}|jaxlib-{jl}"


def _default_device_kind() -> str:
    import jax

    try:
        return jax.devices()[0].device_kind.replace(" ", "_")
    except Exception:  # pragma: no cover - uninitialized backend
        return "unknown"


def _default_world() -> int:
    import jax

    try:
        return int(jax.device_count())
    except Exception:  # pragma: no cover - uninitialized backend
        return 1


def cache_key(program_fingerprint: str, shape_bucket, dtype,
              device_kind: Optional[str] = None,
              world: Optional[int] = None) -> str:
    """The PR-13 kernel-cache key shape with the two fields whose absence
    caused the PR-15 cross-device-count abort: device_kind and world are
    ALWAYS part of the identity (defaulted from the live backend)."""
    dk = device_kind if device_kind is not None else _default_device_kind()
    w = world if world is not None else _default_world()
    bucket = "x".join(str(b) for b in shape_bucket) \
        if isinstance(shape_bucket, (tuple, list)) else str(shape_bucket)
    return f"{program_fingerprint}|{bucket}|{dtype}|{dk}|w{int(w)}"


def export_compiled(fn, *example_args):
    """Serialize-capable export of ``fn`` at the example arguments'
    shapes/dtypes. Returns the ``Exported`` (``.serialize()`` →  bytes,
    ``.call(*args)`` executes). Raises where :func:`export_supported` is
    False — gate on the probe first."""
    import jax

    exp = _export_mod()
    return exp.export(jax.jit(fn))(*example_args)


def compilation_cache_subdir(base: str, world: Optional[int] = None,
                             device_kind: Optional[str] = None) -> str:
    """A world/device-kind-keyed subdirectory for XLA's OWN persistent
    compilation cache (``JAX_COMPILATION_CACHE_DIR``).

    The PR-15 bench aborted glibc when a subprocess with a different
    ``--xla_force_host_platform_device_count`` shared the parent's cache
    directory; the workaround stripped the cache wholesale. Keying the
    directory the same way artifact entries are keyed lets every world
    size share one base without interference — the root fix.
    """
    dk = device_kind if device_kind is not None else _default_device_kind()
    w = world if world is not None else _default_world()
    sub = os.path.join(base, f"{dk}-w{int(w)}")
    os.makedirs(sub, exist_ok=True)
    return sub


class ArtifactCache:
    """Keyed persistent store of serialized compiled programs.

    ``store(key, exported)`` persists ``exported.serialize()`` under the
    key (and always registers the object on the in-process warm map);
    ``lookup(key)`` answers from the warm map first, then deserializes a
    validated on-disk entry. Where ``jax.export`` is unavailable the
    disk tier is inert and the warm map alone carries the zero-cold-start
    contract for the life of the process — the documented degraded mode.

    ``fs`` is the ``LocalFS`` syscall seam (robustness/checkpoint.py) so
    ``FaultyFS`` can tear writes at exactly the points a machine fails.
    """

    def __init__(self, root: str, fs=None):
        from ..robustness.checkpoint import LocalFS

        self.root = str(root)
        self.fs = fs if fs is not None else LocalFS()
        self.fs.makedirs(self.root)
        self._warm: Dict[str, Any] = {}
        self.hits = 0
        self.misses = 0
        self.discards = 0

    # ----------------------------------------------------------- internals
    def _path(self, key: str) -> str:
        name = hashlib.sha256(key.encode()).hexdigest()[:24]
        return os.path.join(self.root, f"art_{name}.json")

    def _discard(self, path: str, why: str):
        self.discards += 1
        _m_events.labels(event="discard").inc()
        warnings.warn(
            f"artifact cache entry discarded ({why}): {path} — falling "
            f"back to recompile", stacklevel=3)
        try:
            self.fs.remove(path)
        except OSError:
            pass

    # -------------------------------------------------------------- bytes
    def save_bytes(self, key: str, payload: bytes,
                   meta: Optional[dict] = None) -> Optional[str]:
        """Atomically persist one entry; None (never an exception) on
        I/O failure — the cache is an accelerator, not a dependency."""
        entry = {
            "version": CACHE_VERSION,
            "key": key,
            "producer": producer_id(),
            "digest": hashlib.sha256(payload).hexdigest(),
            "payload": base64.b64encode(payload).decode("ascii"),
            "meta": dict(meta or {}),
        }
        path = self._path(key)
        tmp = path + ".tmp"
        try:
            with self.fs.open(tmp, "wb") as f:
                f.write(json.dumps(entry, sort_keys=True).encode())
                self.fs.fsync(f)
            self.fs.replace(tmp, path)
        except OSError as e:
            warnings.warn(f"artifact cache save failed ({e!r}): {path} — "
                          f"entry not persisted", stacklevel=2)
            return None
        _m_events.labels(event="store").inc()
        return path

    def load_bytes(self, key: str) -> Optional[bytes]:
        """Validated read: a missing entry is a quiet miss; a corrupt /
        torn / version-drifted / key-mismatched entry is discarded loudly
        and reads as a miss (the caller recompiles)."""
        path = self._path(key)
        if not self.fs.exists(path):
            self.misses += 1
            _m_events.labels(event="miss").inc()
            return None
        try:
            with self.fs.open(path, "rb") as f:
                entry = json.loads(f.read().decode())
        except (OSError, ValueError, UnicodeDecodeError):
            self._discard(path, "unreadable/corrupt")
            return None
        if not isinstance(entry, dict) \
                or entry.get("version") != CACHE_VERSION:
            self._discard(path, f"version drift "
                                f"(entry {entry.get('version')!r}, "
                                f"cache {CACHE_VERSION})")
            return None
        if entry.get("producer") != producer_id():
            self._discard(path, f"producer drift "
                                f"(entry {entry.get('producer')!r}, "
                                f"running {producer_id()!r})")
            return None
        if entry.get("key") != key:
            self._discard(path, "key mismatch (hash collision or tamper)")
            return None
        try:
            payload = base64.b64decode(entry["payload"].encode("ascii"))
        except Exception:
            self._discard(path, "payload undecodable")
            return None
        if hashlib.sha256(payload).hexdigest() != entry.get("digest"):
            self._discard(path, "content digest mismatch (torn write?)")
            return None
        self.hits += 1
        _m_events.labels(event="hit").inc()
        return payload

    # ----------------------------------------------------------- programs
    def store(self, key: str, exported) -> bool:
        """Register a compiled program under ``key``. The in-process warm
        map always takes it; the disk tier additionally persists the
        serialized form when the export capability exists AND the object
        is serializable. True iff the entry was persisted to disk."""
        self._warm[key] = exported
        if not export_supported():
            return False
        ser = getattr(exported, "serialize", None)
        if ser is None:
            return False
        try:
            payload = ser()
        except Exception as e:
            warnings.warn(f"artifact serialize failed ({e!r}) — entry "
                          f"kept in-process only", stacklevel=2)
            return False
        return self.save_bytes(key, payload) is not None

    def lookup(self, key: str):
        """The compiled program for ``key``: the in-process warm map
        first, then a validated deserialization of the disk entry (cached
        back into the warm map). None = recompile."""
        hit = self._warm.get(key)
        if hit is not None:
            self.hits += 1
            _m_events.labels(event="hit").inc()
            return hit
        if not export_supported():
            self.misses += 1
            _m_events.labels(event="miss").inc()
            return None
        payload = self.load_bytes(key)
        if payload is None:
            return None
        try:
            obj = _export_mod().deserialize(bytearray(payload))
        except Exception as e:
            self._discard(self._path(key), f"deserialize failed ({e!r})")
            return None
        self._warm[key] = obj
        return obj

    def stats(self) -> dict:
        return {"root": self.root, "warm_entries": len(self._warm),
                "hits": self.hits, "misses": self.misses,
                "discards": self.discards,
                "export_supported": export_supported()}

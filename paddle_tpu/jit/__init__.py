"""paddle_tpu.jit — compiled execution.

Reference surface: paddle.jit.to_static / paddle.jit.save/load
(python/paddle/fluid/dygraph/jit.py, dygraph_to_static/). TPU-native: tracing
via the functional bridge + jax.jit; the ProgramDesc analog is the jaxpr/HLO
owned by XLA, and `TrainStep` fuses forward+backward+optimizer into ONE
compiled program — the fast path that replaces the reference's per-op executor
loop entirely.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import autograd, random as rng_mod
from ..framework.tensor import Tensor
from .functional import FunctionalModule, tree_to_vals, vals_to_tensors


def _amp_fingerprint():
    """Hashable identity of the ambient AMP mode (None when off). The op
    allow/block lists are part of the identity: they are baked into the
    trace, so two policies must not share a cache entry."""
    from ..amp import amp_state

    st = amp_state()
    if st is None:
        return None
    return (st.get("level"), str(st.get("dtype")),
            frozenset(st.get("white") or ()), frozenset(st.get("black") or ()))


def _interleave_vals(mask, trk, frz):
    full, ti, fi = [], 0, 0
    for m in mask:
        if m:
            full.append(trk[ti])
            ti += 1
        else:
            full.append(frz[fi])
            fi += 1
    return full


def _abstract_key(vals):
    out = []
    for v in jax.tree_util.tree_leaves(vals):
        out.append((tuple(v.shape), str(v.dtype)) if hasattr(v, "shape") else repr(v))
    return tuple(out)


class StaticFunction:
    """@to_static product: shape-cached jitted forward.

    Inference calls run the cached executable. Calls needing grad register
    the whole compiled forward as ONE tape op whose forward AND vjp are
    jitted once per shape key (_grad_step_cached) — no per-call tracing.
    TrainStep still wins for full training loops because it fuses the
    optimizer update into the same program.
    """

    def __init__(self, layer_or_fn, input_spec=None):
        from ..nn import Layer

        if isinstance(layer_or_fn, Layer):
            self.layer = layer_or_fn
            self.fn = None
        else:
            self.layer = getattr(layer_or_fn, "__self__", None)
            self.fn = layer_or_fn
        self.fm = FunctionalModule(self.layer) if self.layer is not None else None
        self._cache: Dict[Any, Callable] = {}

    def _pure(self, training):
        fm = self.fm

        def pure(pvals, bvals, key, args, kwargs):
            fn = None
            if self.fn is not None:
                fn = lambda layer, *a, **k: self.fn.__func__(layer, *a, **k)  # noqa: E731
            return fm.call(pvals, bvals, key, args, kwargs, training=training, fn=fn)

        return pure

    def __call__(self, *args, **kwargs):
        if not _to_static_state["enabled"]:
            # conversion globally off: run the original code eagerly
            if self.fn is not None:
                if self.layer is not None and hasattr(self.fn, "__func__"):
                    return self.fn.__func__(self.layer, *args, **kwargs)
                return self.fn(*args, **kwargs)
            return self.layer.forward(*args, **kwargs)
        if self.fm is None:
            # plain function: jit directly with shape cache
            key = ("fn", _abstract_key(tree_to_vals(args)))
            if key not in self._cache:
                f = self.fn

                def pure(a, kw):
                    ta = vals_to_tensors(a)
                    tk = vals_to_tensors(kw)
                    with autograd.no_grad():
                        return tree_to_vals(f(*ta, **tk))

                self._cache[key] = jax.jit(pure)
            out = self._cache[key](tree_to_vals(args), tree_to_vals(kwargs))
            return vals_to_tensors(out)

        fm = self.fm
        training = self.layer.training
        arg_vals = tree_to_vals(args)
        kw_vals = tree_to_vals(kwargs)
        # grad needed for trainable params OR differentiable inputs (an
        # all-frozen feature extractor must still propagate dL/dx)
        input_needs_grad = any(
            isinstance(o, Tensor) and not o.stop_gradient
            and hasattr(o._value, "dtype")
            and jnp.issubdtype(o._value.dtype, jnp.inexact)
            for o in jax.tree_util.tree_flatten((args, kwargs))[0])
        need_grad = autograd.is_grad_enabled() and (
            any(fm.trainable_mask) or input_needs_grad)
        rng_key = rng_mod.next_key()

        # AMP is ambient python state read while tracing, so it must be part
        # of the cache identity: toggling auto_cast between same-shape calls
        # must not reuse a trace baked under the other mode
        ckey = (training, need_grad, _abstract_key(arg_vals),
                _abstract_key(kw_vals), _amp_fingerprint())
        if ckey not in self._cache:
            pure = self._pure(training)
            self._cache[ckey] = jax.jit(pure)
        jitted = self._cache[ckey]

        if not need_grad:
            out_vals, new_b = jitted(fm.param_values(), fm.buffer_values(), rng_key,
                                     arg_vals, kw_vals)
            fm.bind_buffers(new_b)
            return vals_to_tensors(out_vals)

        # grad path: whole compiled forward as one tape op over trainable params
        # + floating inputs
        bvals = fm.buffer_values()
        frozen = [v for v, m in zip(fm.param_values(), fm.trainable_mask) if not m]

        flat_args, args_treedef = jax.tree_util.tree_flatten((arg_vals, kw_vals))
        n_params = sum(fm.trainable_mask)

        tracked_tensors = [p for p, m in zip(fm.params, fm.trainable_mask) if m]
        # keep the ORIGINAL arg Tensors for tape linkage (a fresh wrapper
        # would sever the user's x from the grad graph and default to
        # stop_gradient=True, silently dropping input grads)
        flat_orig = jax.tree_util.tree_flatten((args, kwargs))[0]
        input_tensors = [
            o if isinstance(o, Tensor) else Tensor(v, _internal=True)
            for o, v in zip(flat_orig, flat_args)
        ]

        if autograd._op_recorder is None:
            # fast path (VERDICT r1 weak #5): jitted forward + jitted vjp
            # cached per shape key — NO per-call tracing. The tape GradNode
            # is wired directly, exactly as call_op would.
            return self._grad_step_cached(
                ckey, jitted, args_treedef, tracked_tensors, input_tensors,
                frozen, bvals, rng_key)

        out_struct = {}

        def op_fn(*tracked):
            full_p = _interleave_vals(fm.trainable_mask,
                                      list(tracked[:n_params]), frozen)
            a_vals, k_vals = jax.tree_util.tree_unflatten(
                args_treedef, list(tracked[n_params:])
            )
            out_vals, new_b = jitted(full_p, bvals, rng_key, a_vals, k_vals)
            flat_out, treedef = jax.tree_util.tree_flatten(out_vals)
            out_struct["treedef"] = treedef
            out_struct["n_out"] = len(flat_out)
            return tuple(flat_out) + tuple(new_b)

        res = autograd.call_op(op_fn, *tracked_tensors, *input_tensors,
                               op_name="to_static")
        if not isinstance(res, tuple):
            res = (res,)
        n_out = out_struct["n_out"]
        out_flat, buf_out = res[:n_out], res[n_out:]
        for b, t in zip(fm.buffers, buf_out):
            b._value = t._value
        out_vals = jax.tree_util.tree_unflatten(out_struct["treedef"], list(out_flat))
        return jax.tree_util.tree_map(
            lambda v: v if isinstance(v, Tensor) else Tensor(v, _internal=True),
            out_vals,
        )

    def _grad_step_cached(self, ckey, jitted, args_treedef, tracked_tensors,
                          input_tensors, frozen, bvals, rng_key):
        """Cached-jit grad dispatch: one jitted forward and one jitted vjp
        per (training, shapes) key. Replaces the per-call ``jax.vjp``
        re-trace of the whole model body with two compiled calls."""
        from ..amp import amp_cast_inputs, amp_state
        from ..framework.autograd import _is_floating

        fm = self.fm
        mask = fm.trainable_mask

        def _arr(v):
            return hasattr(v, "shape") and hasattr(v, "dtype")

        # AMP input casting, as call_op would apply (amp_auto_cast.cc
        # analog): tracked params + array input leaves are the op's tensor
        # args; python-scalar leaves pass through untouched (weak-typed)
        trk_vals = [t._value for t in tracked_tensors]
        leaf_vals = [t._value for t in input_tensors]
        if amp_state() is not None:
            n_trk = len(trk_vals)
            arr_pos = [i for i, v in enumerate(leaf_vals) if _arr(v)]
            cast = amp_cast_inputs(
                "to_static", trk_vals + [leaf_vals[i] for i in arr_pos])
            trk_vals = cast[:n_trk]
            for j, i in enumerate(arr_pos):
                leaf_vals[i] = cast[n_trk + j]
        trk_vals = tuple(trk_vals)
        leaf_vals = tuple(leaf_vals)
        # diff positions among input leaves (params always differentiate)
        diff_inputs = [
            i for i, t in enumerate(input_tensors)
            if not t.stop_gradient and _arr(t._value)
            and _is_floating(t._value)
        ]
        # key on post-cast dtypes + pytree structure (leaf shapes alone
        # can't distinguish two kwarg spellings with identical shapes);
        # python scalars are traced weak-typed, keyed by type only
        sig = tuple((tuple(v.shape), str(v.dtype)) if _arr(v)
                    else ("py", type(v).__name__)
                    for v in trk_vals + leaf_vals)
        gkey = ("gradjit", ckey, tuple(diff_inputs), sig, args_treedef)
        entry = self._cache.get(gkey)
        if entry is None:
            def run(trk, leaves, frz, bv, key):
                a_vals, k_vals = jax.tree_util.tree_unflatten(
                    args_treedef, list(leaves))
                # pytree output: the treedef is read off the first real call
                return jitted(_interleave_vals(mask, trk, frz),
                              list(bv), key, a_vals, k_vals)

            def bwd(trk, leaves, frz, bv, key, cots):
                def closure(trk_d, leaves_d):
                    merged = list(leaves)
                    for j, i in enumerate(diff_inputs):
                        merged[i] = leaves_d[j]
                    out_vals, new_b = run(trk_d, merged, frz, bv, key)
                    return (tuple(jax.tree_util.tree_leaves(out_vals)) +
                            tuple(new_b))

                _, vjp_fn = jax.vjp(
                    closure, tuple(trk),
                    tuple(leaves[i] for i in diff_inputs))
                g_trk, g_in = vjp_fn(tuple(cots))
                return tuple(g_trk) + tuple(g_in)

            entry = {"fwd": jax.jit(run), "bwd": jax.jit(bwd),
                     "bwd_raw": bwd}
            self._cache[gkey] = entry

        frz = tuple(frozen)
        bv = tuple(bvals)
        if autograd._op_profiler is not None:
            import time as _time

            t0 = _time.perf_counter_ns()
            out_vals_tree, new_b = entry["fwd"](trk_vals, leaf_vals, frz, bv,
                                                rng_key)
            autograd._op_profiler("to_static", t0, _time.perf_counter_ns())
        else:
            out_vals_tree, new_b = entry["fwd"](trk_vals, leaf_vals, frz, bv,
                                                rng_key)
        flat_out, out_treedef = jax.tree_util.tree_flatten(out_vals_tree)
        for b, v in zip(fm.buffers, new_b):
            b._value = v

        bwd_jit = entry["bwd"]

        def vjp_fn(cots):
            cot_list = list(cots) if isinstance(cots, (tuple, list)) else [cots]
            if any(getattr(c, "dtype", None) == jax.dtypes.float0
                   for c in jax.tree_util.tree_leaves(cot_list)):
                # float0 (int-output) cotangents can't cross jit; rare —
                # run the same bwd body unjitted
                return entry["bwd_raw"](trk_vals, leaf_vals, frz, bv,
                                        rng_key, tuple(cot_list))
            return bwd_jit(trk_vals, leaf_vals, frz, bv, rng_key,
                           tuple(cot_list))

        all_outs = tuple(flat_out) + tuple(new_b)
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in all_outs]
        diff_tensors = list(tracked_tensors) + [input_tensors[i]
                                                for i in diff_inputs]
        node = autograd.GradNode(
            vjp_fn,
            [(t, t._grad_node, t._out_index) for t in diff_tensors],
            out_avals,
            True,
            name="to_static",
        )
        res = autograd._wrap_outputs(all_outs, node=node, op_name="to_static")
        out_flat = res[:len(flat_out)]
        out_vals = jax.tree_util.tree_unflatten(out_treedef, list(out_flat))
        return jax.tree_util.tree_map(
            lambda v: v if isinstance(v, Tensor) else Tensor(v, _internal=True),
            out_vals,
        )


def to_static(function=None, input_spec=None, build_strategy=None, backend=None):
    """paddle.jit.to_static decorator (fluid/dygraph/jit.py:to_static)."""

    def decorate(f):
        from ..nn import Layer
        from .dy2static import transform_function

        if isinstance(f, Layer):
            fwd = f.forward.__get__(f) if hasattr(f.forward, "__get__") \
                else f.forward
            f.forward = StaticFunction(transform_function(fwd))
            return f
        return StaticFunction(transform_function(f))

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


class TrainStep:
    """One fused, compiled training step: forward + backward + optimizer.

    (loss computation included). The replacement for the reference's executor
    hot loop (§3.1) — everything lands in one XLA program; params/opt slots are
    donated so updates happen in place in HBM.

        step = TrainStep(model, loss_fn, optimizer)
        loss = step(inputs=(x,), labels=(y,))   # params updated in place
        # loss_fn is called as loss_fn(*model_outputs, *labels)

    `grad_comm` (a GradCommConfig or codec name) expresses the data-parallel
    gradient all-reduce EXPLICITLY inside the compiled program (ISSUE 8 /
    EQuARX): the forward+backward runs as explicit SPMD over the mesh's
    batch axes (shard_map), each grad bucket is quantized with the
    configured wire codec, psum'd as integers, and dequantized — all
    in-trace, so XLA's latency-hiding scheduler overlaps the (up to 4x
    smaller) transfers with compute. The cross-step error-feedback residual
    is CARRIED STATE of the jitted step: an in/out pytree threaded through
    every call, checkpointed via `grad_comm_communicator.state_dict()`
    (robustness/distributed_ft.capture_job_state(train_step=...)), so
    crash->resume stays bit-identical. Without a >1-replica batch axis the
    knob is inert and the step compiles exactly as before.
    """

    def __init__(self, model, loss_fn, optimizer, grad_accum_steps=1,
                 batch_spec=None, grad_fn=None, grad_comm=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.fm = FunctionalModule(model)
        self.grad_accum = int(grad_accum_steps)
        # optional external loss+grad engine (e.g. the 1F1B pipeline
        # schedule): grad_fn(train_p, frozen_p, bvals, key, ins, lbls) ->
        # (loss, grads_in_train_p_order); optimizer update/clip/shardings
        # stay the standard path
        self.grad_fn = grad_fn
        # in-trace quantized gradient all-reduce (distributed/grad_comm.py
        # codecs); the communicator owns the bucket plan and the
        # error-feedback residual store between steps
        self._gc_comm = None
        self.comm_stats = None
        if grad_comm is not None:
            from ..distributed.grad_comm import (GradCommConfig,
                                                 GradCommunicator)

            if isinstance(grad_comm, str):
                grad_comm = GradCommConfig(codec=grad_comm)
            if self.grad_accum > 1 or (
                    grad_fn is not None
                    and not getattr(grad_fn, "handles_grad_comm", False)):
                raise ValueError(
                    "TrainStep(grad_comm=...) expresses the gradient "
                    "all-reduce explicitly in-trace; it supports the "
                    "plain fused step (grad_accum_steps == 1) or an "
                    "external grad_fn that marks handles_grad_comm (the "
                    "1F1B pipeline engine) — not this combination")
            self._gc_comm = GradCommunicator(grad_comm)
        self._cache: Dict[Any, Callable] = {}
        self._slots = None
        self._accum = None
        self._accum_count = 0
        # newest cache entry + abstract call signature, kept so
        # memory_analysis() can AOT-lower the exact compiled program
        self._last_ckey = None
        self._last_abstract = None
        # distributed: PartitionSpec for data batches (defaults to sharding the
        # leading dim over the 'data' axis when a mesh is active)
        self._batch_spec = batch_spec

    def _mesh(self):
        from ..distributed import mesh as mesh_mod

        m = mesh_mod.get_mesh()
        if m is not None and m.size > 1:
            return m
        return None

    # ------------------------------------------- in-trace quantized comm
    @property
    def grad_comm_communicator(self):
        """The GradCommunicator carrying this step's in-trace error-feedback
        residuals (None without grad_comm=). Its state_dict()/
        load_state_dict() are the resume surface — capture_job_state
        (robustness/distributed_ft) accepts it as `reducer` (or this whole
        step as `train_step=`)."""
        return self._gc_comm

    def _gc_world(self, mesh):
        """(axes, world) of the in-trace gradient all-reduce: the mesh's
        >1-sized batch axes. world <= 1 leaves the codec path inert —
        a single replica has no wire to compress."""
        if mesh is None or self._gc_comm is None:
            return (), 1
        axes = tuple(ax for ax in ("data", "sharding")
                     if ax in mesh.axis_names and mesh.shape[ax] > 1)
        world = 1
        for ax in axes:
            world *= mesh.shape[ax]
        return axes, world

    def _gc_res_layout(self, mesh):
        """Per-bucket (rows, PartitionSpec) of the carried error-feedback
        residuals: each bucket's residual stacks one row per rank that
        quantizes its own distinct shard. Here every bucket reduces over
        the batch axes, so rows = the reducing world and the spec is the
        batch spec. PipelineTrainStep refines this per bucket — a bucket
        of pipe-OWNED grads has per-(pipe x data)-rank residuals, a
        replicated-param bucket per-data-rank only (a wider spec would
        re-vary the replicated grads and break the schedule's output
        replication)."""
        from jax.sharding import PartitionSpec as P

        from ..distributed import mesh as mesh_mod

        spec = mesh_mod.sanitize_spec(
            self._batch_spec or P(("data", "sharding")), mesh)
        world = self._gc_world(mesh)[1]
        return [(world, spec) for _ in self._gc_buckets()]

    def _gc_buckets(self):
        """Bucket plan over the trainable params (cached by the
        communicator; identical on every rank by construction)."""
        fm = self.fm
        train_params = [p for p, m in zip(fm.params, fm.trainable_mask)
                        if m]
        dtypes = [np.dtype(p._value.dtype) for p in train_params]
        return self._gc_comm.buckets_for(train_params, dtypes=dtypes)

    def _gc_error_feedback(self) -> bool:
        from ..distributed.grad_comm import EF_CODECS

        cfg = self._gc_comm.config
        return cfg.error_feedback and cfg.codec in EF_CODECS

    def _account_gc_step(self, buckets, world):
        """Per-EXECUTED-step wire accounting for the in-trace sync. The
        traced python runs once at compile time, so the compiled program
        cannot count itself — the wire bytes per step are static (bucket
        plan x codec), so each host-side call records one sync into the
        grad_comm metric families with path="traced"."""
        from ..distributed import grad_comm as gc_mod

        cfg = self._gc_comm.config
        comm_bytes = collectives = 0
        for b in buckets:
            if cfg.codec in gc_mod.BLOCK_CODECS:
                comm_bytes += (b.size * gc_mod._WIRE_ITEMSIZE[cfg.codec]
                               + gc_mod.scale_bytes(b.size, cfg.block_size))
                collectives += 2
            elif cfg.codec == "int8":
                comm_bytes += b.size * 1 + 4
                collectives += 2
            elif cfg.codec == "bf16" and b.dtype.itemsize > 2:
                comm_bytes += b.size * 2
                collectives += 1
            else:
                comm_bytes += b.nbytes
                collectives += 1
        gc_mod.record_sync_metrics(cfg.codec, collectives, comm_bytes,
                                   "traced")
        self.comm_stats = {"codec": cfg.codec, "path": "traced",
                           "world": int(world), "n_buckets": len(buckets),
                           "collectives": collectives,
                           "comm_bytes": comm_bytes}
        self._gc_comm.stats = dict(self.comm_stats)

    def _shardings(self, train_p_tensors, slots, in_vals, lbl_vals,
                   gc_res=()):
        """NamedShardings for (train_p, frozen_p, bvals, slots, gc_res,
        key, lr, ins, lbls)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        m = self._mesh()
        from ..distributed import mesh as mesh_mod

        def pspec(p):
            spec = p.dist_spec if getattr(p, "dist_spec", None) is not None else P()
            return mesh_mod.sanitize_spec(spec, m)

        def ns(spec):
            return NamedSharding(m, spec)

        fm = self.fm
        train_params = [p for p, msk in zip(fm.params, fm.trainable_mask) if msk]
        frozen_params = [p for p, msk in zip(fm.params, fm.trainable_mask) if not msk]
        tp_sh = [ns(pspec(p)) for p in train_params]
        fp_sh = [ns(pspec(p)) for p in frozen_params]
        b_sh = [ns(P()) for _ in fm.buffers]
        # ZeRO stage-1/2 (group_sharded 'os'/'os_g'): slots of replicated
        # params still shard over the 'sharding' axis when the optimizer is
        # marked by group_sharded_parallel (distributed/sharding)
        slot_axis = getattr(self.optimizer, "_slot_shard_axis", None)
        slot_deg = m.shape[slot_axis] if (
            slot_axis and m is not None and slot_axis in m.axis_names) else 1

        def slot_spec(p, v):
            if getattr(v, "shape", ()) != tuple(p._value.shape):
                return P()
            from ..distributed.sharding import zero_slot_spec

            return zero_slot_spec(v.shape, pspec(p), slot_axis, slot_deg)

        slot_sh = []
        for p, s in zip(train_params, slots):
            slot_sh.append({k: ns(slot_spec(p, v)) for k, v in s.items()})
        bs = mesh_mod.sanitize_spec(self._batch_spec or P(("data", "sharding")), m)
        data_sh = jax.tree_util.tree_map(
            lambda v: ns(bs if getattr(v, "ndim", 0) >= 1 else P()), in_vals
        )
        lbl_sh = jax.tree_util.tree_map(
            lambda v: ns(bs if getattr(v, "ndim", 0) >= 1 else P()), lbl_vals
        )
        # error-feedback residuals are PER-RANK state (each replica's own
        # local quantization error), carried stacked on a leading world dim
        # and sharded per _gc_res_layout — declaring them replicated would
        # let a host round-trip (checkpoint!) collapse every rank's
        # residual onto rank 0's
        gc_sh = ([ns(spec) for (_r, spec) in self._gc_res_layout(m)]
                 if gc_res else [])
        return (tp_sh, fp_sh, b_sh, slot_sh, gc_sh, ns(P()), ns(P()),
                data_sh, lbl_sh), (ns(P()), tp_sh, b_sh, slot_sh)

    def _build(self, key_shape):
        fm = self.fm
        opt = self.optimizer
        loss_fn = self.loss_fn
        mask = fm.trainable_mask
        clip_cfg = opt._clip_cfg()
        lr_mults = [
            float(getattr(p, "optimize_attr", {}).get("learning_rate", 1.0))
            for p, m in zip(fm.params, mask) if m
        ]
        wds = [opt._param_wd(p) for p, m in zip(fm.params, mask) if m]
        # keep updated params/opt-state pinned to their shardings in-trace
        mesh = self._mesh()
        param_sh = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..distributed import mesh as mesh_mod

            param_sh = [
                NamedSharding(mesh, mesh_mod.sanitize_spec(
                    p.dist_spec if getattr(p, "dist_spec", None) is not None
                    else P(), mesh))
                for p, msk in zip(fm.params, mask) if msk
            ]

        split_params = fm.split_values
        merge_params = fm.merge_values

        accum = max(1, self.grad_accum)

        # ---- in-trace quantized gradient all-reduce (ISSUE 8 / EQuARX):
        # forward+backward as explicit SPMD over the batch axes so the
        # backward produces LOCAL grads, then quantize -> psum-of-int ->
        # dequantize per bucket inside the same trace. gc_step is the whole
        # replacement for the jax.value_and_grad branch below.
        gc_comm = self._gc_comm
        gc_axes, gc_world = self._gc_world(mesh)
        gc_on = gc_comm is not None and gc_world > 1
        gc_step = None
        gc_fused = None
        if gc_on and self.grad_fn is None:
            from jax.sharding import PartitionSpec as P

            from ..distributed import collective as _coll
            from ..distributed import mesh as mesh_lib
            from ..distributed.collective import ReduceOp as _ROp
            from ..framework.tensor import Tensor as _T

            gc_buckets = self._gc_buckets()
            gc_ef = self._gc_error_feedback()
            # ISSUE 13 follow-on: with the kernel flag on, a blockwise
            # codec, a fusable elementwise rule and uniform per-bucket
            # hyperparameters (no clip — it needs the decoded grads), the
            # compiled step keeps the SUMMED WIRE PAYLOAD and the fused
            # dequant+update kernel consumes it per flat bucket — the
            # decoded gradient never materializes in HBM. Flag off (or
            # any precondition missing): the jnp decode path below runs
            # byte-for-byte as before.
            from ..distributed.grad_comm import BLOCK_CODECS as _BLK
            from ..framework.flags import flag as _ka_flag

            if (_ka_flag("FLAGS_kernel_autotune")
                    and gc_comm.config.codec in _BLK
                    and clip_cfg is None and accum == 1):
                from ..ops.pallas import fused_update as _fu

                _spec = _fu.rule_spec(opt)
                if _spec is not None:
                    hypers = []
                    for b in gc_buckets:
                        lms = {lr_mults[pi] for pi in b.param_indices}
                        bwds = {wds[pi] for pi in b.param_indices}
                        if len(lms) > 1 or len(bwds) > 1:
                            hypers = None
                            break
                        hypers.append((lms.pop(), bwds.pop()))
                    if hypers is not None:
                        gc_fused = {"kind": _spec[0], "hyper": _spec[1],
                                    "bucket_hypers": hypers,
                                    "slot_names": _fu._slot_names(
                                        _spec[0])}
            if gc_comm.group is None or \
                    tuple(gc_comm.group.axes) != gc_axes:
                gc_comm.group = _coll.new_group(axes=gc_axes)
            gc_group = gc_comm.group
            bs_spec = mesh_lib.sanitize_spec(
                self._batch_spec or jax.sharding.PartitionSpec(
                    ("data", "sharding")), mesh)

            def _bspec(v):
                return bs_spec if getattr(v, "ndim", 0) >= 1 else P()

            def gc_step(train_p, frozen_p, bvals, gc_res, key, in_vals,
                        lbl_vals):
                in_specs_d = jax.tree_util.tree_map(_bspec, in_vals)
                lbl_specs = jax.tree_util.tree_map(_bspec, lbl_vals)

                def body(tp, fp, bv, res, k, ins, lbls):
                    def local_loss(tp_, bv_, ins_, lbls_, k_):
                        pv = merge_params(list(tp_), list(fp))
                        out_vals, new_b = fm.call(pv, list(bv_), k_, ins_,
                                                  training=True)
                        outs = vals_to_tensors(out_vals)
                        largs = (list(outs) if isinstance(outs,
                                                          (tuple, list))
                                 else [outs])
                        largs += list(vals_to_tensors(lbls_))
                        with autograd.no_grad():
                            loss_t = loss_fn(*largs)
                        return (loss_t._value.astype(jnp.float32),
                                (new_b, out_vals))

                    (loss, (new_b, out_vals)), grads = jax.value_and_grad(
                        local_loss, has_aux=True)(tuple(tp), bv, ins,
                                                  lbls, k)
                    # shard-local mean loss -> global mean (equal shards)
                    lt = _T(loss, _internal=True)
                    _coll.all_reduce(lt, op=_ROp.AVG, group=gc_group)
                    loss = lt._value
                    # quantized bucket all-reduce with the error-feedback
                    # residual threaded through as carried state. Each
                    # residual is PER-RANK (this replica's own quantization
                    # error): carried stacked on a leading world dim and
                    # sharded over the batch axes, so the body sees its own
                    # (1, n) row — and a host round trip (checkpoint)
                    # preserves every rank's row instead of collapsing all
                    # onto rank 0's
                    grads = list(grads)
                    new_res = list(res)
                    payloads = []
                    for gi, b in enumerate(gc_buckets):
                        if len(b.param_indices) == 1:
                            flat = grads[b.param_indices[0]].reshape(-1)
                        else:
                            flat = jnp.concatenate(
                                [grads[pi].reshape(-1)
                                 for pi in b.param_indices])
                        residual = res[gi].reshape(-1) if gc_ef else None
                        if gc_fused is not None:
                            # keep the summed wire payload; the fused
                            # kernel dequantizes inside the update
                            q_sum, scales, nr, _w, _c = \
                                gc_comm.reduce_bucket_payload(
                                    b, flat, gc_world, residual=residual)
                            payloads.append((q_sum, scales))
                            if nr is not None:
                                new_res[gi] = nr.reshape(1, -1)
                            continue
                        reduced, nr, _w, _c = gc_comm.reduce_bucket(
                            b, flat, gc_world, residual=residual)
                        if nr is not None:
                            new_res[gi] = nr.reshape(1, -1)
                        for pi, off, n, shape in zip(
                                b.param_indices, b.offsets, b.numels,
                                b.shapes):
                            grads[pi] = reduced[off:off + n].reshape(
                                shape).astype(grads[pi].dtype)
                    if gc_fused is not None:
                        grads = tuple(payloads)
                    # clip AFTER the sync — global-gradient semantics,
                    # same as the implicit-psum path
                    if clip_cfg is not None:
                        grads = _apply_clip(grads, clip_cfg)
                    # floating buffers computed on the batch shard average
                    # back to one replicated value
                    rep_b = []
                    for v in new_b:
                        if hasattr(v, "dtype") and jnp.issubdtype(
                                v.dtype, jnp.inexact):
                            bt = _T(v, _internal=True)
                            _coll.all_reduce(bt, op=_ROp.AVG,
                                             group=gc_group)
                            v = bt._value
                        rep_b.append(v)
                    return (loss, out_vals, tuple(grads), tuple(rep_b),
                            tuple(new_res))

                f = mesh_lib.compat_shard_map(
                    body, mesh,
                    in_specs=(P(), P(), P(), bs_spec, P(), in_specs_d,
                              lbl_specs),
                    out_specs=(P(), bs_spec, P(), P(), bs_spec))
                loss, out_vals, grads, new_b, new_res = f(
                    tuple(train_p), tuple(frozen_p), tuple(bvals),
                    tuple(gc_res), key, in_vals, lbl_vals)
                # pin the (batch-sharded) outputs' sharding in-trace:
                # with out_shardings left to XLA, the donation aliaser
                # would otherwise pair a replicated donated param with a
                # same-global-shape sharded output and fail on the local
                # byte-size mismatch
                out_ns = jax.sharding.NamedSharding(mesh, bs_spec)
                out_vals = jax.tree_util.tree_map(
                    lambda v: (jax.lax.with_sharding_constraint(v, out_ns)
                               if getattr(v, "ndim", 0) >= 1 else v),
                    out_vals)
                return loss, out_vals, grads, new_b, new_res

        def _gc_fused_update(train_p, slots, payloads, lr):
            """Per-bucket fused dequant+optimizer-update: the summed
            blockwise payload feeds ops/pallas/fused_update directly on
            the flat bucket; per-param values and slots are views split
            back out (the same split the jnp path's scatter does), with
            the scalar slots (beta pows) shared bucket-wide — exact
            because every param steps with identical betas."""
            from ..ops.pallas.fused_update import fused_dequant_update_flat

            kind, hyper = gc_fused["kind"], gc_fused["hyper"]
            names = gc_fused["slot_names"]
            new_tp = list(train_p)
            new_slots = [dict(s) for s in slots]

            def cat(vals):
                return vals[0] if len(vals) == 1 else jnp.concatenate(vals)

            for b, (q_sum, scales), (lm, wd) in zip(
                    gc_buckets, payloads, gc_fused["bucket_hypers"]):
                flat_p = cat([train_p[pi].reshape(-1)
                              for pi in b.param_indices])
                first = slots[b.param_indices[0]]
                flat_slots = {
                    nm: cat([slots[pi][nm].reshape(-1)
                             for pi in b.param_indices]) for nm in names}
                for k2, v2 in first.items():
                    if k2 not in names:
                        flat_slots[k2] = v2      # scalar slots
                new_flat, new_s = fused_dequant_update_flat(
                    flat_p, q_sum, scales, gc_world, flat_slots, lr,
                    kind=kind, hyper=hyper,
                    block_size=gc_comm.config.block_size,
                    bucket_dtype=b.dtype, lm=lm, wd=wd)
                scalars = {k2: v2 for k2, v2 in new_s.items()
                           if k2 not in names}
                for pi, off, n, shape in zip(b.param_indices, b.offsets,
                                             b.numels, b.shapes):
                    np_ = new_flat[off:off + n].reshape(shape).astype(
                        train_p[pi].dtype)
                    sdict = {nm: new_s[nm][off:off + n].reshape(shape)
                             for nm in names}
                    sdict.update(scalars)
                    if param_sh is not None:
                        np_ = jax.lax.with_sharding_constraint(
                            np_, param_sh[pi])
                        sdict = {
                            k2: jax.lax.with_sharding_constraint(
                                v2, param_sh[pi])
                            if getattr(v2, "shape", ()) == tuple(shape)
                            else v2
                            for k2, v2 in sdict.items()}
                    new_tp[pi] = np_
                    new_slots[pi] = sdict
            return new_tp, new_slots

        def pure_step(train_p, frozen_p, bvals, slots, gc_res, key, lr,
                      in_vals, lbl_vals):
            def loss_of(tp, bv, ins, lbls, k):
                pv = merge_params(tp, frozen_p)
                out_vals, new_b = fm.call(pv, bv, k, ins, training=True)
                outs = vals_to_tensors(out_vals)
                largs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
                largs += list(vals_to_tensors(lbls))
                with autograd.no_grad():
                    loss_t = loss_fn(*largs)
                return loss_t._value.astype(jnp.float32), (new_b, out_vals)

            new_gc_res = tuple(gc_res)
            if gc_step is not None:
                loss, out_vals, grads, new_b, new_gc_res = gc_step(
                    train_p, frozen_p, bvals, gc_res, key, in_vals,
                    lbl_vals)
                new_b = list(new_b)   # pytree parity with fm.call's output
                if gc_fused is not None:
                    # `grads` carries the per-bucket wire payloads; the
                    # fused kernel dequantizes inside the update
                    new_tp, new_slots = _gc_fused_update(
                        train_p, slots, grads, lr)
                    return (loss, new_tp, new_b, new_slots, new_gc_res,
                            out_vals)
            elif self.grad_fn is not None:
                if getattr(self.grad_fn, "handles_grad_comm", False) \
                        and gc_on:
                    # the grad engine (1F1B pipeline) runs the quantized
                    # reduction inside its own shard_map body and threads
                    # the error-feedback residuals as carried state
                    loss, grads, new_gc_res = self.grad_fn(
                        train_p, frozen_p, bvals, gc_res, key, in_vals,
                        lbl_vals)
                    new_gc_res = tuple(new_gc_res)
                else:
                    loss, grads = self.grad_fn(
                        train_p, frozen_p, bvals, key, in_vals, lbl_vals)
                loss = loss.astype(jnp.float32)
                new_b, out_vals = bvals, ()
            elif accum == 1:
                (loss, (new_b, out_vals)), grads = jax.value_and_grad(
                    loss_of, has_aux=True
                )(train_p, bvals, in_vals, lbl_vals, key)
            else:
                # micro-batch accumulation: split the leading batch dim into
                # `accum` chunks and scan, averaging grads — one optimizer
                # update per call (reference: GradientMergeOptimizer /
                # pipeline accumulate_steps)
                def reshape_micro(v):
                    return v.reshape((accum, v.shape[0] // accum) + v.shape[1:])

                m_ins = jax.tree_util.tree_map(reshape_micro, in_vals)
                m_lbls = jax.tree_util.tree_map(reshape_micro, lbl_vals)
                keys = jax.random.split(key, accum)

                def micro(carry, xs):
                    bv, gacc = carry
                    ins, lbls, k = xs
                    (l, (nb, ov)), g = jax.value_and_grad(loss_of, has_aux=True)(
                        train_p, bv, ins, lbls, k
                    )
                    gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
                    return (nb, gacc), (l, ov)

                g0 = jax.tree_util.tree_map(
                    lambda v: jnp.zeros(v.shape, jnp.result_type(v, jnp.float32)),
                    list(train_p),
                )
                (new_b, gsum), (losses, outs_stacked) = jax.lax.scan(
                    micro, (bvals, g0), (m_ins, m_lbls, keys)
                )
                grads = jax.tree_util.tree_map(lambda g: g / accum, gsum)
                loss = jnp.mean(losses)
                out_vals = jax.tree_util.tree_map(
                    lambda v: v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:]),
                    outs_stacked,
                )
            if clip_cfg is not None and gc_step is None:
                # the gc path already clipped inside the shard body
                grads = _apply_clip(grads, clip_cfg)
            new_tp, new_slots = [], []
            for i, (pval, g, s, lm, wd) in enumerate(
                zip(train_p, grads, slots, lr_mults, wds)
            ):
                np_, ns_ = opt._update(pval, g.astype(pval.dtype), s, lr, lm, wd)
                np_ = np_.astype(pval.dtype)
                if param_sh is not None:
                    np_ = jax.lax.with_sharding_constraint(np_, param_sh[i])
                    ns_ = {
                        k: jax.lax.with_sharding_constraint(v, param_sh[i])
                        if getattr(v, "shape", ()) == tuple(pval.shape) else v
                        for k, v in ns_.items()
                    }
                new_tp.append(np_)
                new_slots.append(ns_)
            # donated-buffer outputs (params, slots, residuals) come BEFORE
            # out_vals: jax pairs donated inputs with outputs of equal
            # abstract shape in order, and a batch-sharded model output that
            # happens to share a donated param's global shape would steal
            # its alias slot and fail on the local byte-size mismatch
            return loss, new_tp, new_b, new_slots, new_gc_res, out_vals

        return pure_step

    def _compile(self, pure_step, slots, in_vals, lbl_vals, gc_res=()):
        if self._mesh() is None:
            return jax.jit(pure_step, donate_argnums=(0, 3, 4))
        in_sh, _ = self._shardings(None, slots, in_vals, lbl_vals, gc_res)
        # pin updated params/buffers/slots to their input shardings: without
        # this XLA may emit replicated outputs, silently undoing the ZeRO
        # memory profile (and paying an all-gather per step)
        tp_sh, b_sh, slot_sh, gc_sh = (in_sh[0], in_sh[2], in_sh[3],
                                       in_sh[4])
        out_sh = (None, list(tp_sh), list(b_sh),
                  [dict(d) for d in slot_sh], tuple(gc_sh), None)
        return jax.jit(pure_step, donate_argnums=(0, 3, 4),
                       in_shardings=in_sh, out_shardings=out_sh)

    def __call__(self, inputs, labels=()):
        fm = self.fm
        if not isinstance(inputs, (tuple, list)):
            inputs = (inputs,)
        if not isinstance(labels, (tuple, list)):
            labels = (labels,)
        in_vals = tree_to_vals(tuple(inputs))
        lbl_vals = tree_to_vals(tuple(labels))
        opt = self.optimizer
        writer_is_self = getattr(opt, "_slot_writer_is",
                                 lambda s: False)(self)
        if self._slots is None or not writer_is_self:
            # (re-)import optimizer state: first call, OR newer state was
            # written by the eager path / set_state_dict / another
            # TrainStep since our last step (last-writer arbitration).
            # COPIED: this step donates its slot buffers, and donating an
            # array the optimizer still references would leave
            # optimizer._slots reading deleted memory.
            if self._slots is not None and getattr(
                    opt, "_slot_writer", None) not in (None, "eager"):
                # the newer writer is another compiled step: land its
                # slots in opt._slots first, then import
                opt._sync_from_compiled()

            def _carry(p, cur):
                s = opt._slots.get(id(p))
                if not s:
                    return cur if cur is not None else \
                        opt._init_slots(p._value)
                return {k: jnp.array(v, copy=True) for k, v in s.items()}

            train_params = [p for p, m in zip(fm.params, fm.trainable_mask)
                            if m]
            cur_slots = self._slots or [None] * len(train_params)
            self._slots = [_carry(p, cur)
                           for p, cur in zip(train_params, cur_slots)]
        # in-trace grad-comm carried state: the per-bucket error-feedback
        # residuals ride in and out of the jitted step as an aux pytree
        gc_axes, gc_world = self._gc_world(self._mesh())
        gc_on = self._gc_comm is not None and gc_world > 1
        gc_res, gc_buckets = [], None
        if gc_on:
            gc_buckets = self._gc_buckets()
            if self._gc_error_feedback():
                # (rows, bucket_size) per bucket: row r is rank r's OWN
                # error-feedback residual (sharded per _gc_res_layout by
                # _shardings; a checkpoint round trip keeps every row)
                layout = self._gc_res_layout(self._mesh())
                for b, (rows, _spec) in zip(gc_buckets, layout):
                    r = self._gc_comm._residuals.get(b.index)
                    gc_res.append(
                        jnp.zeros((rows, b.size), jnp.float32)
                        if r is None
                        else jnp.asarray(r, jnp.float32).reshape(
                            rows, b.size))
        ckey = (_abstract_key(in_vals), _abstract_key(lbl_vals))
        if ckey not in self._cache:
            self._cache[ckey] = self._compile(
                self._build(ckey), self._slots, in_vals, lbl_vals, gc_res
            )
        step = self._cache[ckey]
        pvals = fm.param_values()
        train_p = [v for v, m in zip(pvals, fm.trainable_mask) if m]
        frozen_p = [v for v, m in zip(pvals, fm.trainable_mask) if not m]
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = rng_mod.next_key()
        bvals = fm.buffer_values()
        if self._mesh() is not None:
            # place every operand on its target sharding (no-op when already
            # there); jit-with-in_shardings rejects mismatched placements
            (tp_sh, fp_sh, b_sh, slot_sh, gc_sh, _k, _l, d_sh, l_sh), _ = \
                self._shardings(None, self._slots, in_vals, lbl_vals,
                                gc_res)
            train_p = [jax.device_put(v, s) for v, s in zip(train_p, tp_sh)]
            frozen_p = [jax.device_put(v, s) for v, s in zip(frozen_p, fp_sh)]
            bvals = [jax.device_put(v, s) for v, s in zip(bvals, b_sh)]
            self._slots = jax.tree_util.tree_map(
                lambda v, s: jax.device_put(v, s), self._slots, slot_sh
            )
            gc_res = [jax.device_put(v, s) for v, s in zip(gc_res, gc_sh)]
            in_vals = jax.tree_util.tree_map(
                lambda v, s: jax.device_put(v, s), in_vals, d_sh
            )
            lbl_vals = jax.tree_util.tree_map(
                lambda v, s: jax.device_put(v, s), lbl_vals, l_sh
            )
        # abstract signature BEFORE the call: donated buffers (params,
        # slots) are deleted by the step, but memory_analysis() only needs
        # their shapes/dtypes
        self._last_ckey = ckey
        self._last_abstract = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype),
            (train_p, frozen_p, bvals, self._slots, gc_res, key, lr,
             in_vals, lbl_vals))
        loss, new_tp, new_b, new_slots, new_gc_res, out_vals = step(
            train_p, frozen_p, bvals, self._slots, gc_res, key, lr,
            in_vals, lbl_vals,
        )
        ti = 0
        for p, m in zip(fm.params, fm.trainable_mask):
            if m:
                p._value = new_tp[ti]
                ti += 1
        fm.bind_buffers(new_b)
        self._slots = new_slots
        if gc_on:
            if len(new_gc_res):
                for b, r in zip(gc_buckets, new_gc_res):
                    self._gc_comm._residuals[b.index] = r
            self._account_gc_step(gc_buckets, gc_world)
        self.optimizer._accumulated_steps += 1
        mark = getattr(self.optimizer, "_mark_slot_writer", None)
        if mark is not None:
            mark(self)
        t = Tensor(loss, _internal=True)
        self.last_outputs = vals_to_tensors(out_vals)
        return t

    def memory_analysis(self, record=True, entry=None):
        """XLA's memory accounting for the newest compiled step: AOT-lower
        the cached program at the last call's abstract signature and read
        ``compiled.memory_analysis()`` (argument/temp/output/alias bytes +
        the derived ``peak_hbm_bytes``). When `record`, the result lands in
        observability.memory's compiled-path registry keyed by this trace-
        cache entry (the ``compiled_peak_hbm_bytes{entry=...}`` gauge) —
        bench.py's ``peak_hbm_bytes_measured`` reads it from here. Returns
        None before the first call or when the backend doesn't report."""
        if self._last_ckey is None or self._last_ckey not in self._cache:
            return None
        try:
            compiled = self._cache[self._last_ckey].lower(
                *self._last_abstract).compile()
        except Exception:
            return None
        from ..observability import memory as obs_mem

        analysis = obs_mem.analyze_compiled(compiled)
        if analysis is not None and record:
            entry = entry or (
                f"train_step:{type(self.model).__name__}:"
                f"{abs(hash(self._last_ckey)) & 0xFFFFFF:06x}")
            obs_mem.record_compiled(entry, analysis)
            analysis = dict(analysis, entry=entry)
        return analysis


def _apply_clip(grads, cfg):
    kind, cval = cfg
    if kind == "global_norm":
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        gnorm = jnp.sqrt(gsq)
        scale = jnp.minimum(1.0, cval / jnp.maximum(gnorm, 1e-12))
        return [g * scale.astype(g.dtype) for g in grads]
    if kind == "norm":
        out = []
        for g in grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            s = jnp.minimum(1.0, cval / jnp.maximum(n, 1e-12))
            out.append(g * s.astype(g.dtype))
        return out
    if kind == "value":
        lo, hi = cval
        return [jnp.clip(g, lo, hi) for g in grads]
    return grads


def save(layer, path, input_spec=None, **config):
    """paddle.jit.save — weights for reload; with input_spec, ALSO the
    deployable inference artifact (StableHLO triple, inference/io.py) that
    paddle_tpu.inference.create_predictor / static.load_inference_model can
    serve from a fresh process.

    Reference saves a translated ProgramDesc + params
    (fluid/dygraph/jit.py:save → __model__/.pdiparams for AnalysisPredictor).
    """
    import pickle

    from ..nn import Layer

    state = {}
    if isinstance(layer, Layer):
        state["state_dict"] = {
            k: np.asarray(v._value) for k, v in layer.state_dict().items()
        }
        state["class"] = type(layer).__name__
    with open(path + ".pdparams" if not path.endswith(".pdparams") else path, "wb") as f:
        pickle.dump(state, f)

    if input_spec and isinstance(layer, Layer):
        from ..inference.io import export_inference_artifact
        from .functional import FunctionalModule

        was_training = layer.training
        layer.eval()
        try:
            fm = FunctionalModule(layer)
            pvals = fm.param_values()
            bvals = fm.buffer_values()
            key = jax.random.key(0)
            feed_specs = []
            for i, spec in enumerate(input_spec):
                # None/-1 dims stay symbolic (shape-polymorphic export)
                shape = tuple(None if (d is None or (isinstance(d, int)
                                                     and d < 0))
                              else int(d) for d in spec.shape)
                name = getattr(spec, "name", None) or f"x{i}"
                feed_specs.append((name, shape, str(np.dtype(spec.dtype))))

            n_p = len(pvals)

            def fn(ws, fs):
                out, _ = fm.call(list(ws[:n_p]), list(ws[n_p:]), key,
                                 tuple(fs), training=False)
                return out

            export_inference_artifact(fn, list(pvals) + list(bvals),
                                      feed_specs, path)
        finally:
            if was_training:
                layer.train()


class TranslatedLayer:
    """Loaded inference artifact as a callable Layer-like (reference:
    fluid/dygraph/io.py TranslatedLayer returned by paddle.jit.load)."""

    def __init__(self, artifact, state=None):
        self._artifact = artifact
        self._state = state or {}
        self.training = False

    def __call__(self, *inputs):
        from ..framework.tensor import Tensor

        vals = [i._value if isinstance(i, Tensor) else np.asarray(i)
                for i in inputs]
        outs = self._artifact.run(vals)
        outs = [Tensor(o, _internal=True) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError(
            "a TranslatedLayer serves a compiled inference program; "
            "re-create the original Layer to continue training "
            "(reference limitation as well)")

    def state_dict(self):
        return dict(self._state)


def load(path, **config):
    """paddle.jit.load: with an inference artifact at `path` (written by
    jit.save(..., input_spec=...) or save_inference_model) returns a callable
    TranslatedLayer; otherwise returns the pickled weights dict."""
    import pickle

    if os.path.exists(path + ".pdmodel"):
        from ..inference.io import InferenceArtifact

        state = {}
        pp = path + ".pdparams"
        if os.path.exists(pp):
            with open(pp, "rb") as f:
                state = pickle.load(f).get("state_dict", {})
        return TranslatedLayer(InferenceArtifact.load(path), state)
    p = path + ".pdparams" if not path.endswith(".pdparams") else path
    with open(p, "rb") as f:
        return pickle.load(f)


_to_static_state = {"enabled": True, "code_level": -1, "verbosity": 0}


def enable_to_static(flag=True):
    """Globally toggle @to_static conversion (reference:
    ProgramTranslator.enable / paddle.jit.enable_to_static): when off,
    StaticFunction.__call__ runs the original eager code."""
    _to_static_state["enabled"] = bool(flag)


def set_code_level(level=100):
    """Reference: dygraph_to_static set_code_level — how much transformed
    code to log. Stored for parity; transformed source is available via
    dy2static.transform_function."""
    _to_static_state["code_level"] = int(level)


def set_verbosity(level=0):
    """Reference: dygraph_to_static logging verbosity knob."""
    _to_static_state["verbosity"] = int(level)


def ignore_module(modules):
    pass


class ProgramTranslator:
    """Singleton facade over the to_static machinery (reference:
    fluid/dygraph/dygraph_to_static/program_translator.py)."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        enable_to_static(flag)

    @property
    def enable_to_static(self):
        return _to_static_state["enabled"]

    def get_code(self, fn):
        """Transformed source of a dygraph function (reference
        get_code)."""
        import inspect

        from .dy2static import transform_function

        return inspect.getsource(transform_function(fn))


class TracedLayer:
    """Trace-based dygraph→static capture (reference:
    fluid/dygraph/jit.py TracedLayer): TracedLayer.trace(layer, inputs)
    runs the layer once under tracing and returns (outputs, traced), where
    traced() replays the compiled program and save_inference_model emits
    the deployable artifact."""

    def __init__(self, layer, static_fn):
        self._layer = layer
        self._fn = static_fn

    @classmethod
    def trace(cls, layer, inputs):
        sf = StaticFunction(layer)
        outs = sf(*inputs)
        return outs, cls(layer, sf)

    def __call__(self, inputs):
        return self._fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kw):
        from ..framework.tensor import Tensor

        # re-derive an input spec from the last traced call's cache keys is
        # fragile; require explicit specs via feed, else save weights-only
        save(self._layer, path, input_spec=feed)
        return path

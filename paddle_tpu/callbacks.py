"""paddle.callbacks namespace (parity: python/paddle/callbacks.py)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, MetricsCallback, ModelCheckpoint,
    ProgBarLogger, ReduceLROnPlateau, VisualDL,
)

"""paddle.cost_model — program cost estimation.

Reference: python/paddle/cost_model/cost_model.py (CostModel.profile_measure
over ProfilerProtobuf) + framework/ir/cost_model.cc — per-op cost feeding
passes and the auto-parallel planner.

TPU-native: XLA already computes an analytical cost model for every compiled
executable; `cost_analysis()` surfaces flops/bytes/transcendentals straight
from the compiler, and wall-time comes from a measured replay. No hand-built
per-op cost tables to maintain — the numbers are the compiler's own.
"""
from __future__ import annotations

import math
import time
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["CostModel", "comm_cost", "zero3_cost", "kernel_roofline",
           "pipeline_cost", "ps_pipeline_cost", "DEVICE_PEAKS",
           "HOST_OFFLOAD_BANDWIDTH_BPS"]

# effective ICI bandwidth per chip for bandwidth-optimal collectives and the
# per-collective launch overhead — rough v5e figures; both overridable per
# call. They only rank alternatives (bucketed vs per-param, codec choices);
# absolute times come from measurement / the XLA cost analysis above.
ICI_BANDWIDTH_BPS = 9e10
COLLECTIVE_LATENCY_S = 5e-6

# per-device-kind compute/memory peaks for the kernel roofline bound
# (ops/pallas/autotune.py): {kind_substring: (peak_flops/s, HBM bytes/s)}.
# Rough public numbers — they only LOWER-BOUND a wall-time measurement so
# the autotuner can reject timings that beat physics (clock noise, a
# candidate that silently skipped work); they never rank candidates.
DEVICE_PEAKS = {
    "v5 lite": (1.97e14, 8.2e11),   # v5e: 197 TFLOP/s bf16, 819 GB/s
    "v5e": (1.97e14, 8.2e11),
    "v5p": (4.59e14, 2.77e12),
    "v4": (2.75e14, 1.2e12),
    "v6": (9.2e14, 1.6e12),
    "cpu": (2e11, 5e10),            # host fallback: conservative
}
_DEFAULT_PEAKS = (1.97e14, 8.2e11)


def kernel_roofline(flops: float, bytes_accessed: float,
                    device_kind: str = "cpu",
                    peaks: Optional[tuple] = None) -> float:
    """Roofline LOWER BOUND on one kernel execution, in seconds.

    ``max(flops / peak_flops, bytes / peak_bandwidth)`` with per-device
    peaks from :data:`DEVICE_PEAKS` (substring match on the PJRT
    ``device_kind``, e.g. ``"TPU v5 lite"``). A measured time below this
    bound is physically impossible — the autotune harness
    (ops/pallas/autotune.py) rejects such measurements as noise instead
    of persisting them as winners. ``peaks`` overrides the table.
    """
    if peaks is None:
        kind = (device_kind or "").lower()
        peaks = _DEFAULT_PEAKS
        for sub, p in DEVICE_PEAKS.items():
            if sub in kind:
                peaks = p
                break
    peak_flops, peak_bw = peaks
    return max(float(flops) / peak_flops, float(bytes_accessed) / peak_bw)

# wire bytes per fp32 gradient byte (grad_comm codecs); the blockwise
# codecs add one fp32 scale per block_size elements on top of the base
# 1-byte/element payload (priced separately below)
_CODEC_RATIO = {"fp32": 1.0, "bf16": 0.5, "int8": 0.25,
                "int8_block": 0.25, "fp8_block": 0.25}
_BLOCKWISE = ("int8_block", "fp8_block")


def comm_cost(grad_bytes: float, world: int, codec: str = "bf16",
              comm_buffer_size_MB: float = 25.0,
              collectives: Optional[int] = None,
              reduce_scatter_only: bool = False,
              bandwidth: float = ICI_BANDWIDTH_BPS,
              latency_s: float = COLLECTIVE_LATENCY_S,
              overlap: bool = False,
              backward_s: float = 0.0,
              block_size: int = 1024) -> dict:
    """Analytic gradient-sync cost for the grad_comm layer.

    A ring all-reduce moves 2*(n-1)/n of the wire bytes through each chip
    (reduce-scatter half + all-gather half); `reduce_scatter_only` models the
    ZeRO stage-2 path where each rank keeps just its shard. The latency term
    is what bucketing amortizes: un-bucketed per-param sync pays it once per
    parameter, bucketed sync once per ~comm_buffer_size_MB bucket. Quantized
    codecs scale the bandwidth term by their wire ratio (int8 adds its scalar
    scale exchange to the collective count).

    `overlap` models the bucket-ready async launch (distributed/overlap.py):
    every bucket except the LAST can hide under the tail of backward —
    bounded by `backward_s`, the compute window still running when the first
    bucket closes. The exposed time can never drop below the last bucket's
    own collective (it closes when backward ends, nothing left to hide
    under). Serial sync exposes everything. The returned
    `exposed_time_s` / `hidden_time_s` / `overlap_efficiency` carry the
    split; `time_s` stays the total comm work either way.

    Gather terms (ZeRO-3): this function prices the GRADIENT direction
    only. The parameter direction — per-bucket all_gathers of the at-rest
    shards (`distributed/sharding/stage3.py`), one (world-1)/world ring
    hop per bucket, prefetched a layer ahead so only the first bucket (and
    any gather outliving its compute window) stays exposed, plus the
    param-HBM-at-rest accounting — lives in :func:`zero3_cost`; compose
    the two for a full stage-3 step estimate.
    """
    try:
        ratio = _CODEC_RATIO[codec]
    except KeyError:
        raise ValueError(f"unknown codec {codec!r}; one of "
                         f"{sorted(_CODEC_RATIO)}") from None
    wire_bytes = float(grad_bytes) * ratio
    if codec in _BLOCKWISE:
        # one fp32 scale per block of fp32 elements: 4B per block_size
        # elements = grad_bytes / block_size of scale traffic
        wire_bytes += float(grad_bytes) / float(block_size)
    n_coll = collectives if collectives is not None else max(
        1, math.ceil(wire_bytes / (comm_buffer_size_MB * 1024 * 1024)))
    if codec in (("int8",) + _BLOCKWISE) and collectives is None:
        n_coll *= 2                      # + per-bucket scale exchange
    if world <= 1:
        return {"codec": codec, "world": int(world), "wire_bytes": 0,
                "collectives": 0, "bytes_through_chip": 0.0, "time_s": 0.0,
                "exposed_time_s": 0.0, "hidden_time_s": 0.0,
                "overlap_efficiency": 0.0}
    hops = (world - 1) / world if reduce_scatter_only else 2 * (world - 1) / world
    through = wire_bytes * hops
    time_s = n_coll * latency_s + through / bandwidth
    hidden = 0.0
    if overlap and n_coll > 0:
        per_coll = time_s / n_coll       # buckets are ~uniform by cap
        hideable = time_s - per_coll     # the last bucket is always exposed
        hidden = min(hideable, max(0.0, float(backward_s)))
    return {
        "codec": codec,
        "world": int(world),
        "wire_bytes": int(wire_bytes),
        "collectives": int(n_coll),
        "bytes_through_chip": through,
        "time_s": time_s,
        "exposed_time_s": time_s - hidden,
        "hidden_time_s": hidden,
        "overlap_efficiency": hidden / time_s if time_s else 0.0,
    }


def zero3_cost(param_bytes: float, world: int,
               comm_buffer_size_MB: float = 25.0,
               bandwidth: float = ICI_BANDWIDTH_BPS,
               latency_s: float = COLLECTIVE_LATENCY_S,
               forward_s: float = 0.0,
               prefetch: bool = True,
               regather_backward: bool = False) -> dict:
    """Analytic parameter-gather cost for ZeRO-3 at-rest sharding
    (distributed/sharding/stage3.py).

    At rest each rank holds `param_bytes / world` (`param_bytes_per_rank`
    — the HBM budget the sharding buys). Forward re-materializes the
    parameters one ~`comm_buffer_size_MB` bucket at a time via all_gather:
    a ring gather moves (world-1)/world of the bucket through each chip,
    plus the per-collective launch latency.

    Synchronous gathers expose everything (`exposed_gather_s_sync`). With
    `prefetch` (the layer-ahead launch on the CollectiveLane), bucket k+1's
    gather hides under layer k's compute: only the FIRST bucket (nothing
    runs before it) plus whatever gather work outlives the `forward_s`
    compute window stays exposed (`exposed_gather_s_prefetched`).

    `regather_backward` doubles the gather work for runtimes that free and
    re-gather for backward; the eager tape here keeps the forward-time
    values as vjp residuals, so the default is False.
    """
    if world <= 1:
        return {"world": int(world), "param_bytes": int(param_bytes),
                "param_bytes_per_rank": int(param_bytes), "n_buckets": 0,
                "gather_time_s": 0.0, "exposed_gather_s_sync": 0.0,
                "exposed_gather_s_prefetched": 0.0, "hidden_gather_s": 0.0}
    per_rank = int(math.ceil(param_bytes / world))
    n_buckets = max(1, math.ceil(
        param_bytes / (comm_buffer_size_MB * 1024 * 1024)))
    hops = (world - 1) / world
    t_bucket = latency_s + (param_bytes / n_buckets) * hops / bandwidth
    passes = 2 if regather_backward else 1
    total = passes * n_buckets * t_bucket
    exposed_sync = total
    if prefetch:
        # the first bucket of each pass is always exposed; the rest hide
        # under the compute window (bounded by forward_s per pass)
        hideable = total - passes * t_bucket
        hidden = min(hideable, max(0.0, float(forward_s)) * passes)
    else:
        hidden = 0.0
    return {
        "world": int(world),
        "param_bytes": int(param_bytes),
        "param_bytes_per_rank": per_rank,
        "n_buckets": int(n_buckets),
        "gather_time_s": total,
        "exposed_gather_s_sync": exposed_sync,
        "exposed_gather_s_prefetched": total - hidden,
        "hidden_gather_s": hidden,
    }


# effective host<->device (PCIe/DMA) bandwidth for the activation-offload
# tier — rough v5e figure, overridable per call; like ICI_BANDWIDTH_BPS it
# only ranks alternatives (remat vs offload), never predicts wall time
HOST_OFFLOAD_BANDWIDTH_BPS = 1.6e10

# per-layer activation policies the pipeline memory planner assigns
PIPELINE_POLICIES = ("none", "remat", "offload")


def pipeline_cost(*, pipe_degree: int, microbatches: int,
                  layers_per_stage: int,
                  activation_bytes_per_layer: float,
                  input_bytes_per_layer: float,
                  layer_flops: float,
                  policies: Optional[Sequence[str]] = None,
                  stash_offload: bool = False,
                  stash_slot_bytes: Optional[float] = None,
                  fixed_bytes: float = 0.0,
                  hbm_budget_bytes: Optional[float] = None,
                  device_kind: str = "cpu",
                  peaks: Optional[tuple] = None,
                  host_bandwidth_bps: float = HOST_OFFLOAD_BANDWIDTH_BPS,
                  ) -> dict:
    """Price ONE per-device 1F1B pipeline train step under an activation
    policy assignment — the pricer behind
    ``distributed/pipeline/memory_plan.plan_memory``.

    The segmented 1F1B schedule (distributed/pipeline/schedule.py) runs
    4M + 4P - 4 stage-work units per step against 4M useful ones, so the
    bubble fraction is (P-1)/(M+P-1) — the term a larger micro-batch count
    M buys down, and what this function prices against the activation
    memory M would otherwise cost (GPipe keeps O(M) residuals; 1F1B keeps
    an S = min(M, 2P-1)-slot input stash + one backward tick's residuals).

    Per-layer ``policies`` (length ``layers_per_stage``) govern what the
    backward tick's local VJP keeps resident:

      "none"     full layer internals stay (``activation_bytes_per_layer``)
                 — cheapest time, biggest memory;
      "remat"    jax.checkpoint per block: only the block INPUT persists
                 (``input_bytes_per_layer``); one extra layer-forward of
                 FLOPs per micro-batch;
      "offload"  remat + the saved block input lives in host memory: ~zero
                 device bytes at rest, the input crosses the host link
                 twice per micro-batch (priced at ``host_bandwidth_bps``).

    ``stash_offload`` moves the S-slot micro-batch input stash to the host
    tier the same way (2 crossings per micro-batch of one
    ``stash_slot_bytes`` slot; one slot stays transient on device).

    Returns a dict with the memory account (``activation_bytes_peak``,
    per-component breakdown), the time account (useful/recompute FLOPs,
    ``time_lower_bound_s`` from the device roofline plus the exposed host
    traffic), ``bubble_fraction``, and — when ``hbm_budget_bytes`` is given
    — ``fits`` plus a human-readable ``why`` naming the binding component.
    All byte inputs are PER-DEVICE (post tensor/sequence sharding).
    """
    P = int(pipe_degree)
    M = int(microbatches)
    L = int(layers_per_stage)
    if P < 1 or M < 1 or L < 1:
        raise ValueError(
            f"pipe_degree/microbatches/layers_per_stage must be >= 1, got "
            f"{P}/{M}/{L}")
    policies = list(policies if policies is not None else ["none"] * L)
    if len(policies) != L:
        raise ValueError(
            f"policies has {len(policies)} entries for {L} layers per stage")
    bad = [p for p in policies if p not in PIPELINE_POLICIES]
    if bad:
        raise ValueError(f"unknown policies {bad}; one of "
                         f"{PIPELINE_POLICIES}")
    if stash_slot_bytes is None:
        stash_slot_bytes = input_bytes_per_layer
    S = min(M, 2 * P - 1)
    bubble = (P - 1) / (M + P - 1)

    # ---- memory: stash + one backward tick's resident VJP residuals
    stash_dev = (stash_slot_bytes if stash_offload
                 else S * stash_slot_bytes)
    stash_host = S * stash_slot_bytes if stash_offload else 0.0
    resident = 0.0          # persists across the whole VJP
    transient = 0.0         # one layer's internals during its recompute
    host_bytes_per_mb = 0.0  # host-link crossings per micro-batch (one way)
    recompute_layers = 0
    for pol in policies:
        if pol == "none":
            resident += activation_bytes_per_layer
        elif pol == "remat":
            resident += input_bytes_per_layer
            transient = max(transient, activation_bytes_per_layer)
            recompute_layers += 1
        else:  # offload
            transient = max(transient, activation_bytes_per_layer
                            + input_bytes_per_layer)
            host_bytes_per_mb += 2.0 * input_bytes_per_layer
            recompute_layers += 1
    if stash_offload:
        host_bytes_per_mb += 2.0 * stash_slot_bytes
    act_peak = stash_dev + resident + transient
    peak = act_peak + float(fixed_bytes)

    # ---- time: device roofline on the schedule's work units + exposed
    # host traffic. Useful work = fwd + recompute(stage) + bwd = 4 units
    # per micro-batch per stage-layer; per-layer remat adds one more
    # layer-forward inside the VJP.
    stage_flops = L * float(layer_flops)
    useful_flops = 4.0 * M * stage_flops
    recompute_flops = M * recompute_layers * float(layer_flops)
    total_flops = (useful_flops + recompute_flops) / (1.0 - bubble)
    compute_s = kernel_roofline(total_flops, 0.0, device_kind, peaks)
    offload_s = M * host_bytes_per_mb / float(host_bandwidth_bps)
    out = {
        "pipe": P, "microbatches": M, "layers_per_stage": L,
        "stash_slots": S,
        "policies": list(policies),
        "stash_offload": bool(stash_offload),
        "bubble_fraction": bubble,
        "activation_bytes_peak": int(act_peak),
        "peak_bytes": int(peak),
        "stash_bytes_device": int(stash_dev),
        "stash_bytes_host": int(stash_host),
        "resident_residual_bytes": int(resident),
        "transient_residual_bytes": int(transient),
        "host_bytes_per_step": int(M * host_bytes_per_mb),
        "recompute_flops": recompute_flops,
        "total_flops": total_flops,
        "compute_lower_bound_s": compute_s,
        "offload_s": offload_s,
        "time_lower_bound_s": compute_s + offload_s,
    }
    if hbm_budget_bytes is not None:
        out["hbm_budget_bytes"] = int(hbm_budget_bytes)
        out["fits"] = peak <= hbm_budget_bytes
        binding = max(
            (("stash", stash_dev), ("residuals", resident + transient),
             ("fixed", float(fixed_bytes))), key=lambda kv: kv[1])[0]
        out["why"] = (
            f"peak {int(peak):,} B vs budget {int(hbm_budget_bytes):,} B "
            f"({'fits' if out['fits'] else 'OVER'}; binding component: "
            f"{binding}; bubble {bubble:.1%} at M={M}, P={P})")
    return out


_PS_WIRE_ELEM_BYTES = {"fp32": 4.0, "int8_block": 1.0, "fp8_block": 1.0}


def ps_pipeline_cost(*, batch: int, uniq_keys: int, dim: int,
                     step_s: float, depth: int = 2, codec: str = "fp32",
                     wire_block: int = 512,
                     wire_bandwidth_bps: float = 1e9,
                     rpc_latency_s: float = 2e-4) -> dict:
    """Price one steady-state step of the ISSUE-20 PS pipeline
    (distributed/ps/pipeline.py): a compiled dense step of ``step_s``
    overlapped at ``depth`` with the pull of the next batch's
    ``uniq_keys`` embedding rows and the push of the previous step's row
    grads, each ``uniq_keys * dim`` elements quantized per ``codec`` plus
    per-block fp32 scales and uint64 keys on the wire.

    depth 1 serializes pull -> step -> push; depth >= 2 hides wire time
    behind compute, so the steady-state step is max(step, pull, push) and
    the *exposed* remainders are what bench_gate watches. The model only
    ranks codec/depth/capacity choices — absolute times come from
    tools/ps_bench.py measurement."""
    if codec not in _PS_WIRE_ELEM_BYTES:
        raise ValueError(f"unknown PS wire codec {codec!r}; one of "
                         f"{sorted(_PS_WIRE_ELEM_BYTES)}")
    u, d = int(uniq_keys), int(dim)
    numel = u * d
    scale_b = (0.0 if codec == "fp32"
               else 4.0 * math.ceil(numel / float(wire_block)))
    one_way = numel * _PS_WIRE_ELEM_BYTES[codec] + scale_b + 8.0 * u
    t_pull = one_way / float(wire_bandwidth_bps) + float(rpc_latency_s)
    t_push = one_way / float(wire_bandwidth_bps) + float(rpc_latency_s)
    if int(depth) <= 1:
        step_total = t_pull + float(step_s) + t_push
        exposed_pull, exposed_push = t_pull, t_push
    else:
        step_total = max(float(step_s), t_pull, t_push)
        exposed_pull = max(0.0, t_pull - float(step_s))
        exposed_push = max(0.0, t_push - float(step_s))
    return {
        "depth": int(depth), "codec": codec,
        "wire_bytes_per_step": int(2 * one_way),
        "pull_s": t_pull, "push_s": t_push, "step_s": float(step_s),
        "exposed_pull_s": exposed_pull, "exposed_push_s": exposed_push,
        "steady_step_s": step_total,
        "examples_per_s": int(batch) / step_total if step_total else 0.0,
        "wire_bound": step_total > float(step_s),
    }


class CostModel:
    def __init__(self):
        self._costs: Dict[str, dict] = {}

    def profile_measure(self, startup_program=None, main_program=None,
                        device="tpu", fetch_cost_list=("time",),
                        feed: Optional[dict] = None, fetch_list=None,
                        repeat: int = 5):
        """Compile main_program, read XLA's analytical cost, measure wall
        time over `repeat` replays. Returns
        {time_ms, flops, bytes_accessed, utilization_pct?}."""
        import jax

        from . import static

        exe = static.Executor()
        if startup_program is not None:
            exe.run(startup_program)
        main_program = main_program or static.default_main_program()
        feed = feed or {}

        # one run to build + compile the cached executable
        exe.run(main_program, feed=feed, fetch_list=fetch_list)
        t0 = time.perf_counter()
        for _ in range(repeat):
            res = exe.run(main_program, feed=feed, fetch_list=fetch_list)
        dt = (time.perf_counter() - t0) / repeat

        del res
        out = {"time_ms": dt * 1e3}
        out.update(self.static_cost(main_program, feed, fetch_list))
        self._costs["main"] = out
        return out

    def static_cost(self, program, feed=None, fetch_list=None) -> dict:
        """XLA analytical cost of the program's forward replay:
        flops / bytes accessed / transcendentals."""
        import jax
        import jax.numpy as jnp

        from . import static

        feed = feed or {}
        feed_names = [n for n in program.feeds if n in feed]
        feed_vids = [program.feeds[n] for n in feed_names]
        ext_ids = sorted(program.externals)

        def replay(ext_vals, feed_vals):
            env = dict(zip(ext_ids, ext_vals))
            env.update(zip(feed_vids, feed_vals))
            for rec in program.ops:
                ins = [env[s[1]] if s[0] == "var" else s[1]
                       for s in rec.arg_spec]
                o = rec.fn(*ins, **rec.kwargs)
                if rec.multi:
                    for oid, ov in zip(rec.out_ids, o):
                        env[oid] = ov
                else:
                    env[rec.out_ids[0]] = o
            if fetch_list:
                ids = static.Executor._fetch_ids(program, fetch_list)
                return tuple(env[ref] for kind, ref in ids if kind == "var")
            return tuple(env[rec.out_ids[0]] for rec in program.ops[-1:])

        ext_vals = [program.externals[v]._value for v in ext_ids]
        feed_vals = [jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        compiled = jax.jit(replay).lower(ext_vals, feed_vals).compile()
        ca = compiled.cost_analysis()
        # jax < 0.5 returns a one-element LIST of per-device dicts;
        # newer jaxes return the dict itself
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        return {
            "flops": float(ca.get("flops", 0.0)),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
            "transcendentals": float(ca.get("transcendentals", 0.0)),
        }

    comm_cost = staticmethod(comm_cost)
    zero3_cost = staticmethod(zero3_cost)
    pipeline_cost = staticmethod(pipeline_cost)

    def get_cost(self, key="main"):
        return self._costs.get(key)

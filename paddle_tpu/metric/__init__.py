"""paddle.metric (reference: python/paddle/metric/metrics.py:180-592)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Top-k accuracy (metrics.py:180)."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).sum()
            accs.append(float(c) / max(n, 1))
            self.correct[i] += int(c)
        self.total += n
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.correct = [0] * len(self.topk)
        self.total = 0

    def accumulate(self):
        res = [c / max(self.total, 1) for c in self.correct]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via the reference's thresholded-bucket algorithm (metrics.py:Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bucket = np.clip(
            (pos_prob * self.num_thresholds).astype(np.int64), 0, self.num_thresholds
        )
        is_pos = labels.astype(bool)
        np.add.at(self._stat_pos, bucket[is_pos], 1)
        np.add.at(self._stat_neg, bucket[~is_pos], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        # trapezoid over buckets, vectorized as a prefix sum (identical
        # math to the reference's high-to-low scalar loop)
        pos = np.asarray(self._stat_pos, np.float64)[::-1]
        neg = np.asarray(self._stat_neg, np.float64)[::-1]
        cp, cn = np.cumsum(pos), np.cumsum(neg)
        auc = float(((cp + (cp - pos)) * (cn - (cn - neg)) / 2.0).sum())
        denom = float(cp[-1]) * float(cn[-1]) if cp.size else 0.0
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (paddle.metric.accuracy)."""
    pred = _np(input)
    lbl = _np(label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lbl.ndim == pred.ndim:
        lbl = lbl.squeeze(-1)
    acc = (idx == lbl[..., None]).any(-1).mean()
    return Tensor(np.asarray(acc, np.float32))

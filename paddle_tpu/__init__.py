"""paddle_tpu — a TPU-native deep-learning framework with the PaddlePaddle API.

Built new against JAX/XLA (compute), pallas (custom kernels), pjit/GSPMD
(parallelism). The reference capability surface is documented in SURVEY.md; the
public namespace mirrors python/paddle/__init__.py of the reference.
"""
from __future__ import annotations

import warnings as _warnings

_warnings.filterwarnings("ignore", message=".*truncated to dtype.*")

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    CPUPlace, CUDAPlace, Parameter, Place, TPUPlace, Tensor, bfloat16,
    complex64, complex128, device_count, enable_grad, float16, float32, float64,
    get_default_dtype, get_device, get_flags, grad, int8, int16, int32, int64,
    is_compiled_with_cuda, is_grad_enabled, no_grad, seed, set_default_dtype,
    set_device, set_flags, set_grad_enabled, to_tensor, uint8,
)
from .framework import bool  # noqa: F401,A004
from .framework.dtype import convert_dtype  # noqa: F401
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401

# the full functional namespace (paddle.add, paddle.matmul, ...)
from .tensor import *  # noqa: F401,F403
from .tensor import is_tensor  # noqa: F401

# static/dygraph mode switch: always-dygraph frontend; enable_static is honored
# by the paddle_tpu.static facade (jit-compiled programs)
_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def in_static_mode():
    return _static_mode[0]


def _import_submodules():
    """Wire up subpackages lazily-but-eagerly: grown as modules land."""
    import importlib

    mod_names = [
        "nn",
        "optimizer",
        "io",
        "metric",
        "amp",
        "jit",
        "static",
        "vision",
        "text",
        "distributed",
        "distribution",
        "autograd",
        "device",
        "hapi",
        "incubate",
        "onnx",
        "profiler",
        "sparse",
        "fft",
        "signal",
        "geometric",
        "hub",
        "cost_model",
        "inference",
        "interop",
        "observability",
        "robustness",
        "linalg",
        "regularizer",
        "callbacks",
        "sysconfig",
        "version",
    ]
    g = globals()
    for m in mod_names:
        try:
            g[m] = importlib.import_module(f".{m}", __name__)
        except ImportError:
            pass


_import_submodules()

# hoist frequently-used entry points when available
try:
    from .framework.io import load, save  # noqa: F401
except ImportError:
    pass
try:
    from .hapi.model import Model  # noqa: F401
    from .hapi.model_summary import flops, summary  # noqa: F401
except ImportError:
    pass
try:
    from .nn.initializer._global import set_global_initializer  # noqa: F401
except ImportError:
    pass


# ---------------------------------------------------------------- misc shims
from .distributed.parallel import DataParallel  # noqa: F401,E402
from .framework.device import XPUPlace  # noqa: F401,E402

dtype = _np_dtype = None
from .framework import dtype as _dtype_mod  # noqa: E402

dtype = _dtype_mod.DType if hasattr(_dtype_mod, "DType") else str


def iinfo(dtype_):
    """paddle.iinfo over numpy (reference: paddle.iinfo)."""
    import numpy as _np

    from .framework.dtype import dtype_name

    return _np.iinfo(_np.dtype(dtype_name(dtype_)))


def finfo(dtype_):
    import numpy as _np

    from .framework.dtype import dtype_name

    name = dtype_name(dtype_)
    if name == "bfloat16":
        import jax.numpy as _jnp

        class _BF16Info:
            bits = 16
            eps = float(_jnp.finfo(_jnp.bfloat16).eps)
            min = float(_jnp.finfo(_jnp.bfloat16).min)
            max = float(_jnp.finfo(_jnp.bfloat16).max)
            tiny = float(_jnp.finfo(_jnp.bfloat16).tiny)
            dtype = "bfloat16"

        return _BF16Info()
    return _np.finfo(_np.dtype(name))


def get_cudnn_version():
    """No CUDA on this stack (reference returns the cudnn build version)."""
    return None


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


class LazyGuard:
    """reference: paddle.LazyGuard — defer parameter initialization. Init is
    already lazy-cheap here (numpy host init, no device traffic until use),
    so the guard is a no-op context for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def batch(reader, batch_size, drop_last=False):
    """paddle.batch (reference fluid reader decorator)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

# ----------------------------------------------- reference top-level parity
from .framework.device import CPUPlace as _CPUPlace  # noqa: E402
from .framework.param_attr import ParamAttr  # noqa: F401,E402
from .framework.tensor import create_parameter  # noqa: F401,E402

CUDAPinnedPlace = _CPUPlace  # pinned host staging dissolves into PJRT
NPUPlace = XPUPlace  # NPU (Ascend) place alias: a non-TPU device tag


def check_shape(shape):
    """Validate a shape argument (reference: paddle.check_shape in
    fluid/layers/utils.py: ints or a 1-D integer tensor; -1 allowed once)."""
    from .framework.tensor import Tensor

    if isinstance(shape, Tensor):
        if len(shape.shape) != 1:
            raise ValueError("shape tensor must be 1-D")
        return
    dims = list(shape)
    # NB: builtins, not the shadowing paddle.sum
    if len([d for d in dims if int(d) == -1]) > 1:
        raise ValueError("only one dimension may be -1")
    for d in dims:
        if int(d) < -1:
            raise ValueError(f"invalid dimension {d}")


def disable_signal_handler():
    """Reference: paddle.disable_signal_handler — the C++ runtime installed
    SIGSEGV/SIGBUS handlers worth disabling when embedding; the TPU build
    installs none, so this is a supported no-op."""


def tolist(x):
    """paddle.tolist (reference: tensor/manipulation.py tolist)."""
    return x.tolist()

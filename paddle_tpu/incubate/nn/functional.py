"""paddle.incubate.nn.functional — fused functional ops.

Reference: incubate/nn/functional/{fused_multi_head_attention.py,
fused_feed_forward.py} over fused_attention_op.cu / fused_feedforward_op.cu.
Each call is ONE traced composition — XLA emits the fused kernels, attention
goes through F.scaled_dot_product_attention (pallas flash on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.autograd import call_op
from ...framework.tensor import Tensor
from ...nn import functional as F

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_linear", "fused_linear_activation"]


def _ln(v, w, b, eps):
    mu = jnp.mean(v.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(v.astype(jnp.float32), axis=-1, keepdims=True)
    out = (v.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out.astype(v.dtype)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.0, attn_dropout_rate=0.0,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1,
        num_heads=None, name=None):
    """One fused block: [pre-LN] → qkv → attention → out-proj → residual →
    [post-LN] (fused_attention_op.cu semantics). qkv_weight: [3, H, N, D]
    or [3H, H] reference layouts both accepted."""
    def fn(xv, qkvw, lw, *rest):
        named = dict(zip(rest_names, rest))
        b, s, h = xv.shape
        hn = xv
        if pre_layer_norm:
            hn = _ln(xv, named.get("pre_ln_scale"), named.get("pre_ln_bias"),
                     pre_ln_epsilon)
        if qkvw.ndim == 4:  # [3, n, d, H] reference fused layout
            three, n, d, _ = qkvw.shape
            w = qkvw.reshape(3 * n * d, h).T            # [H, 3nd]
        else:
            n = num_heads or 0
            w = qkvw.T if qkvw.shape[0] != h else qkvw  # [H, 3H]
            d = (w.shape[1] // 3) // max(n, 1) if n else None
        qkv = hn @ w
        if "qkv_bias" in named:
            qkv = qkv + named["qkv_bias"].reshape(-1)
        nh = n if n else (num_heads or 1)
        dh = qkv.shape[-1] // 3 // nh
        qkv = qkv.reshape(b, s, 3, nh, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if "attn_mask" in named:
            m = named["attn_mask"]
            logits = logits + m.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, -1)
        out = attn @ lw
        if "linear_bias" in named:
            out = out + named["linear_bias"]
        out = xv + out
        if not pre_layer_norm:
            out = _ln(out, named.get("ln_scale"), named.get("ln_bias"),
                      ln_epsilon)
        return out

    rest_names, rest_vals = [], []
    for nm, val in (("pre_ln_scale", pre_ln_scale),
                    ("pre_ln_bias", pre_ln_bias),
                    ("qkv_bias", qkv_bias), ("linear_bias", linear_bias),
                    ("ln_scale", ln_scale), ("ln_bias", ln_bias),
                    ("attn_mask", attn_mask)):
        if val is not None:
            rest_names.append(nm)
            rest_vals.append(val)
    return call_op(fn, x, qkv_weight, linear_weight, *rest_vals,
                   op_name="fused_multi_head_attention")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      ring_id=-1, name=None):
    """[pre-LN] → linear1 → act → linear2 → residual → [post-LN]
    (fused_feedforward_op.cu)."""
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]

    def fn(xv, w1, w2, *rest):
        named = dict(zip(rest_names, rest))
        hn = xv
        if pre_layer_norm:
            hn = _ln(xv, named.get("ln1_scale"), named.get("ln1_bias"),
                     ln1_epsilon)
        z = hn @ w1
        if "linear1_bias" in named:
            z = z + named["linear1_bias"]
        z = act(z)
        z = z @ w2
        if "linear2_bias" in named:
            z = z + named["linear2_bias"]
        out = xv + z
        if not pre_layer_norm:
            out = _ln(out, named.get("ln2_scale"), named.get("ln2_bias"),
                      ln2_epsilon)
        return out

    rest_names, rest_vals = [], []
    for nm, val in (("linear1_bias", linear1_bias),
                    ("linear2_bias", linear2_bias),
                    ("ln1_scale", ln1_scale), ("ln1_bias", ln1_bias),
                    ("ln2_scale", ln2_scale), ("ln2_bias", ln2_bias)):
        if val is not None:
            rest_names.append(nm)
            rest_vals.append(val)
    return call_op(fn, x, linear1_weight, linear2_weight, *rest_vals,
                   op_name="fused_feedforward")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(xv, w, *rest):
        w = w.T if transpose_weight else w
        out = xv @ w
        return out + rest[0] if rest else out

    args = [x, weight] + ([bias] if bias is not None else [])
    return call_op(fn, *args, op_name="fused_linear")


def fused_linear_activation(x, weight, bias=None, activation="gelu",
                            trans_x=False, trans_y=False, name=None):
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "none": lambda v: v}[activation]

    def fn(xv, w, *rest):
        a = xv.T if trans_x else xv
        b = w.T if trans_y else w
        out = a @ b
        if rest:
            out = out + rest[0]
        return act(out)

    args = [x, weight] + ([bias] if bias is not None else [])
    return call_op(fn, *args, op_name="fused_linear_activation")

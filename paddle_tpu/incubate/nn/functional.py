"""paddle.incubate.nn.functional — fused functional ops.

Reference: incubate/nn/functional/{fused_multi_head_attention.py,
fused_feed_forward.py} over fused_attention_op.cu / fused_feedforward_op.cu.
Each call is ONE traced composition — XLA emits the fused kernels, attention
goes through F.scaled_dot_product_attention (pallas flash on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.autograd import call_op
from ...framework.tensor import Tensor
from ...nn import functional as F

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_linear", "fused_linear_activation",
           "fused_linear_cross_entropy"]


def _ln(v, w, b, eps):
    mu = jnp.mean(v.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(v.astype(jnp.float32), axis=-1, keepdims=True)
    out = (v.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out.astype(v.dtype)


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.0, attn_dropout_rate=0.0,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1,
        num_heads=None, name=None):
    """One fused block: [pre-LN] → qkv → attention → out-proj → residual →
    [post-LN] (fused_attention_op.cu semantics). qkv_weight: [3, H, N, D]
    or [3H, H] reference layouts both accepted."""
    def fn(xv, qkvw, lw, *rest):
        named = dict(zip(rest_names, rest))
        b, s, h = xv.shape
        hn = xv
        if pre_layer_norm:
            hn = _ln(xv, named.get("pre_ln_scale"), named.get("pre_ln_bias"),
                     pre_ln_epsilon)
        if qkvw.ndim == 4:  # [3, n, d, H] reference fused layout
            three, n, d, _ = qkvw.shape
            w = qkvw.reshape(3 * n * d, h).T            # [H, 3nd]
        else:
            n = num_heads or 0
            w = qkvw.T if qkvw.shape[0] != h else qkvw  # [H, 3H]
            d = (w.shape[1] // 3) // max(n, 1) if n else None
        qkv = hn @ w
        if "qkv_bias" in named:
            qkv = qkv + named["qkv_bias"].reshape(-1)
        nh = n if n else (num_heads or 1)
        dh = qkv.shape[-1] // 3 // nh
        qkv = qkv.reshape(b, s, 3, nh, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scale = 1.0 / jnp.sqrt(jnp.asarray(dh, jnp.float32))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if "attn_mask" in named:
            m = named["attn_mask"]
            logits = logits + m.astype(logits.dtype)
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, -1)
        out = attn @ lw
        if "linear_bias" in named:
            out = out + named["linear_bias"]
        out = xv + out
        if not pre_layer_norm:
            out = _ln(out, named.get("ln_scale"), named.get("ln_bias"),
                      ln_epsilon)
        return out

    rest_names, rest_vals = [], []
    for nm, val in (("pre_ln_scale", pre_ln_scale),
                    ("pre_ln_bias", pre_ln_bias),
                    ("qkv_bias", qkv_bias), ("linear_bias", linear_bias),
                    ("ln_scale", ln_scale), ("ln_bias", ln_bias),
                    ("attn_mask", attn_mask)):
        if val is not None:
            rest_names.append(nm)
            rest_vals.append(val)
    return call_op(fn, x, qkv_weight, linear_weight, *rest_vals,
                   op_name="fused_multi_head_attention")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, mode=None,
                      ring_id=-1, name=None):
    """[pre-LN] → linear1 → act → linear2 → residual → [post-LN]
    (fused_feedforward_op.cu)."""
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]

    def fn(xv, w1, w2, *rest):
        named = dict(zip(rest_names, rest))
        hn = xv
        if pre_layer_norm:
            hn = _ln(xv, named.get("ln1_scale"), named.get("ln1_bias"),
                     ln1_epsilon)
        z = hn @ w1
        if "linear1_bias" in named:
            z = z + named["linear1_bias"]
        z = act(z)
        z = z @ w2
        if "linear2_bias" in named:
            z = z + named["linear2_bias"]
        out = xv + z
        if not pre_layer_norm:
            out = _ln(out, named.get("ln2_scale"), named.get("ln2_bias"),
                      ln2_epsilon)
        return out

    rest_names, rest_vals = [], []
    for nm, val in (("linear1_bias", linear1_bias),
                    ("linear2_bias", linear2_bias),
                    ("ln1_scale", ln1_scale), ("ln1_bias", ln1_bias),
                    ("ln2_scale", ln2_scale), ("ln2_bias", ln2_bias)):
        if val is not None:
            rest_names.append(nm)
            rest_vals.append(val)
    return call_op(fn, x, linear1_weight, linear2_weight, *rest_vals,
                   op_name="fused_feedforward")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(xv, w, *rest):
        w = w.T if transpose_weight else w
        out = xv @ w
        return out + rest[0] if rest else out

    args = [x, weight] + ([bias] if bias is not None else [])
    return call_op(fn, *args, op_name="fused_linear")


def fused_linear_activation(x, weight, bias=None, activation="gelu",
                            trans_x=False, trans_y=False, name=None):
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu,
           "none": lambda v: v}[activation]

    def fn(xv, w, *rest):
        a = xv.T if trans_x else xv
        b = w.T if trans_y else w
        out = a @ b
        if rest:
            out = out + rest[0]
        return act(out)

    args = [x, weight] + ([bias] if bias is not None else [])
    return call_op(fn, *args, op_name="fused_linear_activation")


# ---------------------------------------------------------------------------
# fused (chunked) linear + softmax cross-entropy
# ---------------------------------------------------------------------------

def fused_linear_cross_entropy(x, weight, labels, bias=None,
                               vocab_chunk=8192, reduction="mean",
                               ignore_index=-100, transposed_weight=False,
                               name=None):
    """Cross-entropy over `x @ weight (+bias)` WITHOUT materializing the
    [N, V] logits (reference capability: fused softmax+CE ops,
    c_softmax_with_cross_entropy; technique: blockwise/chunked CE).

    The vocab axis is processed in chunks under lax.scan: each step does one
    [N, H] x [H, C] MXU matmul, folds it into a running online logsumexp and
    picks the label logit if it falls in the chunk. Peak activation memory is
    O(N * vocab_chunk) instead of O(N * V) — at GPT vocab 50k and 8k tokens
    that is ~12x less HBM for the loss tail. Backward recomputes each
    chunk's softmax from the saved logsumexp (flash-attention-style
    rematerialization): dx accumulates softmax_c @ W_c^T, dW_c = x^T @
    (softmax_c - onehot_c).

    x: [N, H] (flatten [B, S, H] first), weight: [H, V] (paddle Linear
    layout), labels: [N] int. Returns the reduced loss (or [N] with
    reduction='none').
    """
    import functools

    import jax
    import jax.numpy as jnp

    from ...framework.autograd import call_op

    H = int(x.shape[-1])
    V = int(weight.shape[0 if transposed_weight else -1])
    C = min(int(vocab_chunk), V)
    n_chunks = (V + C - 1) // C
    Vp = n_chunks * C  # padded vocab; padding columns masked to -inf

    def _pad_wb(wv, bv):
        """Pad weight/bias to the chunk grid ONCE, outside the scan (a pad
        in the scan body would re-materialize the full embedding per
        step unless XLA hoists it)."""
        if transposed_weight:
            wp = jnp.pad(wv, ((0, Vp - V), (0, 0)))
        else:
            wp = jnp.pad(wv, ((0, 0), (0, Vp - V)))
        bp = jnp.pad(bv, (0, Vp - V)) if bv is not None else None
        return wp, bp

    def _w_chunk(wp, start):
        """[H, C] weight chunk from the pre-padded weight; transposed
        layout ([V, H], e.g. a tied embedding) slices rows and transposes
        the CHUNK (fuses into the dot — never materializes a full [H, V]
        transpose)."""
        if transposed_weight:
            return jax.lax.dynamic_slice_in_dim(wp, start, C, axis=0).T
        return jax.lax.dynamic_slice_in_dim(wp, start, C, axis=1)

    @functools.partial(jax.custom_vjp, nondiff_argnums=())
    def _core(xv, wv, bv, lbl):
        lse, picked = _fwd_state(xv, wv, bv, lbl)
        return lse - picked

    def _fwd_state(xv, wv, bv, lbl):
        xf = xv.astype(jnp.float32)
        N = xf.shape[0]
        wp, bp = _pad_wb(wv, bv)

        def step(carry, c):
            m, s, picked = carry
            start = c * C
            w_c = _w_chunk(wp, start)
            logit = jnp.dot(xf, w_c.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
            if bp is not None:
                b_c = jax.lax.dynamic_slice_in_dim(bp, start, C, axis=0)
                logit = logit + b_c.astype(jnp.float32)
            col = jnp.arange(C) + start
            logit = jnp.where(col[None, :] < V, logit, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(logit, -1))
            s = s * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(logit - m_new[:, None]), -1)
            in_chunk = (lbl >= start) & (lbl < start + C)
            idx = jnp.clip(lbl - start, 0, C - 1)
            mine = jnp.take_along_axis(logit, idx[:, None], 1)[:, 0]
            picked = jnp.where(in_chunk, mine, picked)
            return (m_new, s, picked), None

        init = (jnp.full((N,), -jnp.inf), jnp.zeros((N,)),
                jnp.zeros((N,)))
        (m, s, picked), _ = jax.lax.scan(step, init, jnp.arange(n_chunks))
        return m + jnp.log(s), picked

    def _core_fwd(xv, wv, bv, lbl):
        lse, picked = _fwd_state(xv, wv, bv, lbl)
        return lse - picked, (xv, wv, bv, lbl, lse)

    def _core_bwd(res, g):
        xv, wv, bv, lbl, lse = res
        xf = xv.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        wp, bp = _pad_wb(wv, bv)

        def step(carry, c):
            dx = carry
            start = c * C
            w_c = _w_chunk(wp, start)
            logit = jnp.dot(xf, w_c.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
            if bp is not None:
                b_c = jax.lax.dynamic_slice_in_dim(bp, start, C, axis=0)
                logit = logit + b_c.astype(jnp.float32)
            col = jnp.arange(C) + start
            valid = col[None, :] < V
            soft = jnp.where(valid, jnp.exp(logit - lse[:, None]), 0.0)
            onehot = (lbl[:, None] == col[None, :]).astype(jnp.float32)
            dlogit = (soft - onehot) * gf[:, None]        # [N, C]
            dx = dx + jnp.dot(dlogit, w_c.astype(jnp.float32).T,
                              preferred_element_type=jnp.float32)
            dw_c = jnp.dot(xf.T, dlogit,
                           preferred_element_type=jnp.float32)
            db_c = jnp.sum(dlogit, 0)
            return dx, (dw_c, db_c)

        dx0 = jnp.zeros_like(xf)
        dx, (dw_chunks, db_chunks) = jax.lax.scan(
            step, dx0, jnp.arange(n_chunks))
        if transposed_weight:
            # [n_chunks, H, C] -> [Vp, H] -> [V, H]
            dw = jnp.moveaxis(dw_chunks, 1, 2).reshape(Vp, H)[:V]
        else:
            # [n_chunks, H, C] -> [H, Vp] -> [H, V]
            dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(H, Vp)[:, :V]
        db = db_chunks.reshape(Vp)[:V] if bv is not None else None
        return (dx.astype(xv.dtype), dw.astype(wv.dtype),
                db.astype(bv.dtype) if bv is not None else None, None)

    _core.defvjp(_core_fwd, _core_bwd)

    if reduction not in ("mean", "sum", "none"):
        raise ValueError(
            f"reduction must be 'mean', 'sum' or 'none', got {reduction!r}")

    def fn(xv, wv, *rest):
        i = 0
        bv = None
        if bias is not None:
            bv = rest[i]
            i += 1
        lbl = rest[i].reshape(-1).astype(jnp.int32)
        safe = jnp.where(lbl == ignore_index, 0, lbl)
        per = _core(xv, wv, bv, safe)
        mask = (lbl != ignore_index)
        # labels outside [0, V) fall in no chunk → picked stays 0 and the
        # loss would be silently inflated; surface them as NaN instead
        # (the full-logits path would NaN/crash on the same input)
        oob = mask & ((lbl < 0) | (lbl >= V))
        per = jnp.where(oob, jnp.nan, jnp.where(mask, per, 0.0))
        if reduction == "mean":
            return jnp.sum(per) / jnp.maximum(
                jnp.sum(mask.astype(jnp.float32)), 1.0)
        if reduction == "sum":
            return jnp.sum(per)
        return per

    args = [x, weight] + ([bias] if bias is not None else []) + [labels]
    return call_op(fn, *args, op_name="fused_linear_cross_entropy")

"""paddle.incubate.nn — fused layers + functional fused ops.

Reference: python/paddle/incubate/nn/ (FusedMultiHeadAttention,
FusedFeedForward, fused functional ops) backed by
operators/fused/{fused_attention_op.cu, fused_feedforward_op.cu}.

TPU-native: "fused" is XLA's default — one traced composition compiles to
fused HLO, and attention additionally rides the pallas flash kernel. These
classes/functions keep the reference API so fused-model code ports 1:1.
"""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedFeedForward, FusedMultiHeadAttention, FusedTransformerEncoderLayer,
    ResNetUnit,
)

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "ResNetUnit", "functional"]

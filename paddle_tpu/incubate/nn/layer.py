"""Fused layer classes (reference: incubate/nn/layer/fused_transformer.py)."""
from __future__ import annotations

import numpy as np

from ...nn.initializer import Constant, XavierUniform
from ...nn.layer.layers import Layer
from ...nn import functional as F
from . import functional as IF


class FusedMultiHeadAttention(Layer):
    """incubate.nn.FusedMultiHeadAttention — one fused attention block."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.normalize_before = normalize_before
        self.head_dim = embed_dim // num_heads
        h, n, d = embed_dim, num_heads, self.head_dim
        self.qkv_weight = self.create_parameter(
            shape=[3, n, d, h], default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            shape=[3, n, d], is_bias=True)
        self.linear_weight = self.create_parameter(
            shape=[h, h], default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter(shape=[h], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            shape=[h], default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(shape=[h], is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[h], default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(shape=[h], is_bias=True)
        self._epsilon = epsilon
        self.dropout_rate = dropout_rate

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            ln_epsilon=self._epsilon, num_heads=self.num_heads,
            training=self.training)


class FusedFeedForward(Layer):
    """incubate.nn.FusedFeedForward — one fused FFN block."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            shape=[d_model, dim_feedforward],
            default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            shape=[dim_feedforward], is_bias=True)
        self.linear2_weight = self.create_parameter(
            shape=[dim_feedforward, d_model],
            default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(shape=[d_model],
                                                  is_bias=True)
        self.ln1_scale = self.create_parameter(
            shape=[d_model], default_initializer=Constant(1.0))
        self.ln1_bias = self.create_parameter(shape=[d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            shape=[d_model], default_initializer=Constant(1.0))
        self.ln2_bias = self.create_parameter(shape=[d_model], is_bias=True)

    def forward(self, src, cache=None):
        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            activation=self.activation,
            pre_layer_norm=self.normalize_before,
            ln1_epsilon=self._epsilon, ln2_epsilon=self._epsilon,
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """incubate.nn.FusedTransformerEncoderLayer: the two fused blocks
    composed (reference fused_transformer.py)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class ResNetUnit(Layer):
    """Fused conv+BN(+add+act) block (reference: resnet_unit_op.cc /
    incubate.nn.ResNetUnit): one unit = Conv2D → BN [→ + shortcut(conv→BN)]
    → activation, composed here so XLA emits the fused kernels the CUDA op
    hand-wrote."""

    def __init__(self, num_channels_x, num_filters, filter_size, stride=1,
                 momentum=0.9, eps=1e-5, data_format="NCHW", act="relu",
                 fuse_add=False, has_shortcut=False, use_global_stats=False,
                 is_test=False, filter_x_attr=None, scale_x_attr=None,
                 bias_x_attr=None, moving_mean_x_name=None,
                 moving_var_x_name=None, num_channels_z=None,
                 stride_z=1, filter_z_attr=None, scale_z_attr=None,
                 bias_z_attr=None, moving_mean_z_name=None,
                 moving_var_z_name=None):
        super().__init__()
        from ... import nn

        if act not in ("relu", "identity", None):
            raise ValueError(
                f"ResNetUnit: unsupported act {act!r} (relu/identity)")
        self._fuse_add = fuse_add
        self._has_shortcut = has_shortcut
        self._act = act
        pad = (filter_size - 1) // 2
        self.conv_x = nn.Conv2D(num_channels_x, num_filters, filter_size,
                                stride=stride, padding=pad, bias_attr=False,
                                weight_attr=filter_x_attr,
                                data_format=data_format)
        self.bn_x = nn.BatchNorm2D(num_filters, momentum=momentum,
                                   epsilon=eps, weight_attr=scale_x_attr,
                                   bias_attr=bias_x_attr,
                                   data_format=data_format,
                                   use_global_stats=use_global_stats)
        if has_shortcut:
            self.conv_z = nn.Conv2D(num_channels_z or num_channels_x,
                                    num_filters, 1, stride=stride_z,
                                    bias_attr=False,
                                    weight_attr=filter_z_attr,
                                    data_format=data_format)
            self.bn_z = nn.BatchNorm2D(num_filters, momentum=momentum,
                                       epsilon=eps, weight_attr=scale_z_attr,
                                       bias_attr=bias_z_attr,
                                       data_format=data_format,
                                       use_global_stats=use_global_stats)

    def forward(self, x, z=None):
        out = self.bn_x(self.conv_x(x))
        if self._has_shortcut:
            out = out + self.bn_z(self.conv_z(z if z is not None else x))
        elif self._fuse_add:
            if z is None:
                raise ValueError(
                    "ResNetUnit(fuse_add=True) requires the residual input z")
            out = out + z
        if self._act == "relu":
            out = F.relu(out)
        return out

"""paddle.incubate.optimizer — LookAhead / ModelAverage wrappers.

Reference: python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py}.
Both are host-side parameter bookkeeping around any inner optimizer; the
slow/accumulated weights live as device arrays and the sync math runs as
(small) jitted updates.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k inner steps, then slow <- slow + alpha * (fast - slow); fast <- slow
    (reference lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow: Dict[int, jnp.ndarray] = {}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k:
            return
        for p in self.inner_optimizer._parameter_list:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = self._slow[id(p)] = p._value
                continue
            slow = slow + self.alpha * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.inner_optimizer.clear_grad()
        return [], []


class ModelAverage:
    """Running average of parameters applied at eval time (reference
    modelaverage.py: average_window ratio, apply/restore)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters or [])
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum: Dict[int, jnp.ndarray] = {}
        self._cnt = 0
        self._backup: Dict[int, jnp.ndarray] = {}

    def step(self):
        self._cnt += 1
        for p in self._parameter_list:
            cur = self._sum.get(id(p))
            self._sum[id(p)] = p._value if cur is None else cur + p._value
        if self._cnt > self.max_w:
            # restart the window (the reference's sliding restart)
            for p in self._parameter_list:
                self._sum[id(p)] = self._sum[id(p)] / self._cnt
            self._cnt = 1

    def apply(self, executor=None, need_restore=True):
        for p in self._parameter_list:
            if need_restore:
                self._backup[id(p)] = p._value
            s = self._sum.get(id(p))
            if s is not None and self._cnt:
                p._value = (s / self._cnt).astype(p._value.dtype)

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))

"""Auto checkpoint / resume.

Reference: fluid/incubate/checkpoint/auto_checkpoint.py —
AutoCheckpointChecker:71 (env-gated enablement), TrainEpochRange:265 (epoch
bookkeeping persisted to a filesystem so a preempted/restarted job resumes at
the right epoch). TPU-native storage: orbax-style directory layout on any
LocalFS-interface filesystem; model/optimizer state via paddle.save.

    for epoch in train_epoch_range(10, save_dir="ckpt", job_id="j1",
                                   state={"model": model, "opt": opt}):
        train_one_epoch(...)

On restart with the same job_id, completed epochs are skipped and the state
objects are restored from the newest checkpoint.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["AutoCheckpointChecker", "TrainEpochRange", "train_epoch_range",
           "ExeTrainStatus"]


class AutoCheckpointChecker:
    """Env-gated enablement (checker reads PADDLE_RUNNING_ENV /
    PADDLE_JOB_ID like the reference's :71)."""

    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.hdfs_home = os.environ.get("PADDLE_EDL_HDFS_HOME", "")
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")

    def get_job_checkpoint_path(self, base):
        return os.path.join(base, self.job_id or "default_job")

    def valid(self):
        return bool(self.job_id) or True  # local mode always allowed


class ExeTrainStatus:
    def __init__(self, epoch_no=-1, checkpoint_path=""):
        self.epoch_no = epoch_no
        self.checkpoint_path = checkpoint_path


class TrainEpochRange:
    """Epoch-range bookkeeping (reference :265): iterate epochs, checkpoint
    state at each epoch end, resume past completed epochs on restart."""

    def __init__(self, max_epoch_num, name="train", save_dir="auto_ckpt",
                 job_id=None, state=None, fs=None, save_checkpoint_inter=0):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default_job")
        self.dir = os.path.join(save_dir, self.job_id, name)
        self.state = state or {}
        self.save_inter = save_checkpoint_inter
        self._last_save = 0.0
        os.makedirs(self.dir, exist_ok=True)
        self._meta_path = os.path.join(self.dir, "range.json")
        self._restore()

    # -- persistence --------------------------------------------------------
    def _restore(self):
        self.restored_from = None
        self.start_epoch = 0
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            if meta.get("max_epoch_num") == self.max_epoch_num:
                self.start_epoch = int(meta.get("next_epoch", 0))
                ckpt = meta.get("checkpoint")
                if ckpt and os.path.exists(ckpt + ".pdparams"):
                    self._load_state(ckpt)
                    self.restored_from = ckpt

    def _save_state(self, epoch):
        from ... import load, save

        ckpt = os.path.join(self.dir, f"epoch_{epoch}")
        payload = {}
        for key, obj in self.state.items():
            if hasattr(obj, "state_dict"):
                payload[key] = obj.state_dict()
            else:
                payload[key] = obj
        save(payload, ckpt + ".pdparams")
        with open(self._meta_path, "w") as f:
            json.dump({"max_epoch_num": self.max_epoch_num,
                       "next_epoch": epoch + 1, "checkpoint": ckpt,
                       "ts": time.time()}, f)
        # retire older epoch files
        for name in os.listdir(self.dir):
            if name.startswith("epoch_") and \
                    name != f"epoch_{epoch}.pdparams":
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:
                    pass

    def _load_state(self, ckpt):
        from ... import load

        payload = load(ckpt + ".pdparams")
        for key, obj in self.state.items():
            if key in payload and hasattr(obj, "set_state_dict"):
                obj.set_state_dict(payload[key])

    # -- iteration ----------------------------------------------------------
    def get(self):
        for epoch in range(self.start_epoch, self.max_epoch_num):
            yield epoch
            self._save_state(epoch)

    def __iter__(self):
        return self.get()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None, **kwargs):
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter or 0,
                           **kwargs)

"""Auto checkpoint / resume.

Reference: fluid/incubate/checkpoint/auto_checkpoint.py —
AutoCheckpointChecker:71 (env-gated enablement), TrainEpochRange:265 (epoch
bookkeeping persisted to a filesystem so a preempted/restarted job resumes at
the right epoch). Persistence rides on robustness/checkpoint.py: every epoch
commits an atomic `step_NNNNNN/` checkpoint (manifest + crc32), and restart
resumes from the newest checkpoint that passes validation — a corrupt or
partial checkpoint (crash mid-save) is skipped, falling back to the previous
valid one instead of poisoning the resumed run.

    for epoch in train_epoch_range(10, save_dir="ckpt", job_id="j1",
                                   state={"model": model, "opt": opt}):
        train_one_epoch(...)

On restart with the same job_id, completed epochs are skipped and the state
objects are restored from the newest valid checkpoint.
"""
from __future__ import annotations

import os
import time

from ...robustness.checkpoint import CheckpointManager

__all__ = ["AutoCheckpointChecker", "TrainEpochRange", "train_epoch_range",
           "ExeTrainStatus"]


class AutoCheckpointChecker:
    """Env-gated enablement (checker reads PADDLE_RUNNING_ENV /
    PADDLE_JOB_ID like the reference's :71)."""

    def __init__(self):
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.hdfs_home = os.environ.get("PADDLE_EDL_HDFS_HOME", "")
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")

    def get_job_checkpoint_path(self, base):
        return os.path.join(base, self.job_id or "default_job")

    def valid(self, local_mode=None):
        """Auto-checkpoint engages only inside the EDL environment
        (reference :71: PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT plus a
        job id and a storage home). `local_mode=True` — or the
        PADDLE_TPU_AUTO_CKPT_LOCAL=1 env — is the explicit escape hatch for
        single-host runs without the EDL stack."""
        if local_mode is None:
            local_mode = os.environ.get(
                "PADDLE_TPU_AUTO_CKPT_LOCAL", "") == "1"
        if local_mode:
            return True
        return (self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"
                and bool(self.job_id) and bool(self.hdfs_home))


class ExeTrainStatus:
    def __init__(self, epoch_no=-1, checkpoint_path=""):
        self.epoch_no = epoch_no
        self.checkpoint_path = checkpoint_path


class TrainEpochRange:
    """Epoch-range bookkeeping (reference :265): iterate epochs, checkpoint
    state at each epoch end, resume past completed epochs on restart."""

    def __init__(self, max_epoch_num, name="train", save_dir="auto_ckpt",
                 job_id=None, state=None, fs=None, save_checkpoint_inter=0,
                 keep_last_n=3, preemption_handler=None):
        self.max_epoch_num = int(max_epoch_num)
        self.name = name
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default_job")
        self.dir = os.path.join(save_dir, self.job_id, name)
        self.state = state or {}
        self.save_inter = save_checkpoint_inter
        self._last_save = 0.0
        # preemption tolerance (ISSUE 10): a robustness.PreemptionHandler
        # checked at every epoch boundary — a latched SIGTERM/flag turns
        # the epoch-end save into an emergency commit (reason="preemption",
        # retention-GC exempt, never throttled) and stops the range with
        # `preempted=True`; the same job_id resumes past completed epochs
        self.preemption_handler = preemption_handler
        self.preempted = False
        self.ckpt = CheckpointManager(self.dir, keep_last_n=keep_last_n,
                                      fs=fs)
        self._restore()

    # -- persistence --------------------------------------------------------
    def _restore(self):
        self.restored_from = None
        self.start_epoch = 0
        found = self.ckpt.load_latest()
        if found is None:
            return
        payload, step, manifest = found
        meta = manifest.get("metadata") or {}
        if meta.get("max_epoch_num") not in (None, self.max_epoch_num):
            return  # a different run shape under the same job dir: start over
        self.start_epoch = int(step) + 1
        for key, obj in self.state.items():
            if key in payload and hasattr(obj, "set_state_dict"):
                obj.set_state_dict(payload[key])
        self.restored_from = self.ckpt.step_path(step)

    def _save_state(self, epoch, emergency=False):
        now = time.time()
        if not emergency and self.save_inter \
                and (now - self._last_save) < self.save_inter \
                and epoch + 1 < self.max_epoch_num:
            return  # throttled; the final epoch always checkpoints
        payload = {}
        for key, obj in self.state.items():
            if hasattr(obj, "state_dict"):
                payload[key] = obj.state_dict()
            else:
                payload[key] = obj
        metadata = {"max_epoch_num": self.max_epoch_num,
                    "name": self.name, "job_id": self.job_id}
        if emergency:
            # the preemption commit: tagged so keep-last-N GC exempts it,
            # timed onto the emergency_save_ms gauge, never throttled
            from ...robustness.preemption import timed_emergency_save

            timed_emergency_save(self.ckpt, payload, epoch,
                                 metadata=metadata)
        else:
            self.ckpt.save(payload, epoch, metadata=metadata)
        self._last_save = now

    # -- iteration ----------------------------------------------------------
    def get(self):
        for epoch in range(self.start_epoch, self.max_epoch_num):
            yield epoch
            ph = self.preemption_handler
            if ph is not None and ph.should_stop():
                # epoch boundary hit: commit an emergency checkpoint of
                # the just-finished epoch and stop the range resumably
                self.preempted = True
                self._save_state(epoch, emergency=True)
                return
            self._save_state(epoch)

    def __iter__(self):
        return self.get()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None, **kwargs):
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter or 0,
                           **kwargs)

from .auto_checkpoint import (  # noqa: F401
    AutoCheckpointChecker, ExeTrainStatus, TrainEpochRange, train_epoch_range,
)

"""PSLib-style fleet facade over the native PS runtime.

Reference: python/paddle/fluid/incubate/fleet/parameter_server/pslib/
__init__.py (the DownpourSGD fleet singleton:
init/init_worker/init_server/run_server/stop_worker, table save/load/
shrink, distributed_optimizer -> DownpourOptimizer) backed by
fleet_wrapper.cc (~20k LoC of pslib client calls). TPU-native: the same
lifecycle delegates to TheOnePSRuntime — the TCP TLV PS with the C++
MemorySparseTable — so the legacy entry points drive the real
parameter-server subsystem, not a shim around nothing.
"""
from __future__ import annotations

import os
from typing import Optional

__all__ = ["fleet", "PSLib", "DownpourOptimizer"]


class PSLib:
    def __init__(self):
        self._runtime = None
        self._role_maker = None
        self._inited = False

    # ---- lifecycle (reference pslib __init__.py Fleet surface) ------------
    def init(self, role_maker=None):
        from .....distributed.ps import TheOnePSRuntime

        self._role_maker = role_maker
        self._runtime = TheOnePSRuntime.current()
        self._inited = True
        return self

    def _rt(self):
        if not self._inited:
            self.init()
        from .....distributed.ps import TheOnePSRuntime

        # track the CURRENT runtime: caching the one captured at init
        # would silently save/load a stale client's tables after a new
        # runtime registers itself
        if (self._runtime is None
                or self._runtime is not TheOnePSRuntime._current):
            self._runtime = TheOnePSRuntime.current()
        return self._runtime

    def init_server(self, model_dir: Optional[str] = None, tables=None,
                    **kwargs):
        """tables: {table_id: create_table kwargs} — loading a model_dir
        needs the table configs first (the wire format stores rows, not
        the table's dim/optimizer config, matching the reference where
        the config comes from the program, not the checkpoint)."""
        rt = self._rt()
        ep = rt.init_server()
        if model_dir:
            # load THROUGH the just-started server (a fresh LocalPs here
            # would warm a disconnected in-process store instead)
            from .....distributed.ps import PsClient

            loader = PsClient([ep])
            try:
                import re

                for tid, kw in (tables or {}).items():
                    loader.create_table(int(tid), **kw)

                ids = sorted({
                    int(m.group(1)) for name in os.listdir(model_dir)
                    for m in [re.fullmatch(
                        r"table_(\d+)(?:\.shard\d+)?", name)] if m})
                for tid in ids:
                    loader.load(tid, os.path.join(model_dir,
                                                  f"table_{tid}"))
            finally:
                loader.close()
        return ep

    def run_server(self):
        return self._rt().run_server()

    def init_worker(self, endpoints=None):
        rt = self._rt()
        if endpoints:
            rt.init_worker(endpoints)
        elif rt.client is None:
            from .....distributed.ps import LocalPs

            rt.client = LocalPs()
        return rt.client

    def stop_worker(self):
        self._rt().stop_worker()  # stops the communicator AND closes sockets

    def stop_server(self):
        rt = self._rt()
        if rt.server is not None:
            rt.server.stop()
            rt.server = None

    def barrier_worker(self):
        from .....distributed.env import get_world_size
        from .....distributed.fleet import UtilBase

        if get_world_size() <= 1:
            return  # nothing to rendezvous with
        UtilBase().barrier()  # a FAILED barrier must raise, not be skipped

    # ---- worker/server identity -------------------------------------------
    def is_first_worker(self):
        from .....distributed.env import get_rank

        return get_rank() == 0

    def worker_index(self):
        from .....distributed.env import get_rank

        return get_rank()

    def worker_num(self):
        from .....distributed.env import get_world_size

        return get_world_size()

    def server_num(self):
        return 1 if self._rt().server is not None else 0

    # ---- model/table lifecycle (fleet_wrapper.cc save/load/shrink) --------
    def _client(self):
        c = self._rt().client
        if c is None:
            c = self.init_worker()
        return c

    def _table_ids(self):
        c = self._client()
        tables = getattr(c, "tables", None)
        if tables is not None:  # LocalPs holds them in-process
            return sorted(tables)
        return c.table_ids()  # PsClient asks the server (covers tables
        # created by OTHER clients, not just this one's)

    def save_persistables(self, executor=None, dirname=".", **kwargs):
        """One file per table under dirname (reference mode-0 save)."""
        os.makedirs(dirname, exist_ok=True)
        c = self._client()
        for tid in self._table_ids():
            c.save(tid, os.path.join(dirname, f"table_{tid}"))
        return dirname

    def save_one_table(self, table_id, path, **kwargs):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._client().save(int(table_id), path)

    def load_model(self, dirname, **kwargs):
        import re

        c = self._client()
        # logical names are table_<id>; the rpc client saves per-shard
        # files table_<id>.shard<i> and re-appends the suffix on load,
        # so load by DEDUPED logical id, never by shard filename
        ids = sorted({int(m.group(1)) for name in os.listdir(dirname)
                      for m in [re.fullmatch(r"table_(\d+)(?:\.shard\d+)?",
                                             name)] if m})
        for tid in ids:
            c.load(tid, os.path.join(dirname, f"table_{tid}"))

    def load_one_table(self, table_id, path, **kwargs):
        self._client().load(int(table_id), path)

    def shrink_sparse_table(self, decay=0.98, threshold=1.0, **kwargs):
        """Decay shows, drop cold rows on every sparse table; returns
        total dropped rows (fleet_wrapper.cc ShrinkSparseTable)."""
        c = self._client()
        return sum(c.shrink(tid, decay=decay, threshold=threshold)
                   for tid in self._table_ids())

    def clear_model(self):
        """Drop every row (reference clear_model): a shrink that decays
        shows to zero and keeps nothing."""
        c = self._client()
        for tid in self._table_ids():
            c.shrink(tid, decay=0.0, threshold=float("inf"))

    # ---- optimizer ---------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        return DownpourOptimizer(optimizer, strategy, self)


class DownpourOptimizer:
    """reference pslib DownpourOptimizer / optimizer_factory.py: splits
    the program into dense (local optimizer) and sparse (PS tables)
    halves. Here the sparse half already lives behind
    distributed_lookup_table / heter_embedding (push on backward), so
    minimize is the local optimizer step plus the async communicator's
    send window when one is configured."""

    def __init__(self, optimizer, strategy=None, fleet_obj=None):
        self._inner_opt = optimizer
        self._strategy = strategy or {}
        self._fleet = fleet_obj

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self._inner_opt.step()
        self._inner_opt.clear_grad()
        rt = self._fleet._rt() if self._fleet else None
        if rt is not None and rt.communicator is not None:
            rt.communicator.flush()
        return [], []


fleet = PSLib()

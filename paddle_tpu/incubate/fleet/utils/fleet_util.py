"""FleetUtil: the legacy fleet metrics/model utility surface.

Reference: python/paddle/fluid/incubate/fleet/utils/fleet_util.py
(FleetUtil: rank0 logging, global AUC/metrics all-reduced over workers,
day/pass model save-load naming, donefiles, online pass intervals) and
paddle/fluid/framework/fleet/metrics.cc (the bucketed global metrics).

TPU-native framing: the metric state lives host-side as numpy buckets /
running sums (exactly how paddle.metric.Auc already tracks them); the
cross-worker reduction rides fleet.util.all_reduce (host collective) —
there is no scope-variable plumbing because there is no Scope; metrics
are owned by the GlobalMetrics accumulator or any paddle.metric.Auc.
"""
from __future__ import annotations

import logging
import os
import re
from typing import Optional

import numpy as np

__all__ = ["FleetUtil", "GlobalMetrics"]

_logger = logging.getLogger(__name__)


def _brace_expand(spec):
    """'{20190720..20190722}' -> ['20190720','20190721','20190722'];
    plain space/comma-separated lists pass through (the reference shells
    out to `echo` for this; no shell here)."""
    if isinstance(spec, (list, tuple)):
        return [str(s) for s in spec]
    spec = str(spec).strip()
    m = re.fullmatch(r"\{(\d+)\.\.(\d+)\}", spec)
    if m:
        lo, hi = m.group(1), m.group(2)
        width = len(lo)
        return [str(i).zfill(width) for i in range(int(lo), int(hi) + 1)]
    return [s for s in re.split(r"[\s,]+", spec) if s]


def _bucket_auc(pos, neg):
    """AUC + total instances from pos/neg score-bucket counts (the
    reference's trapezoid accumulation, metrics.cc / fleet_util.py
    get_global_auc) — vectorized as a prefix-sum so million-bucket
    monitors stay cheap."""
    pos = np.asarray(pos, np.float64).reshape(-1)[::-1]
    neg = np.asarray(neg, np.float64).reshape(-1)[::-1]
    cp, cn = np.cumsum(pos), np.cumsum(neg)
    area = float(((cp + (cp - pos)) * (cn - (cn - neg)) / 2.0).sum())
    tot_pos, tot_neg = float(cp[-1]) if cp.size else 0.0,         float(cn[-1]) if cn.size else 0.0
    total = tot_pos + tot_neg
    if tot_pos * tot_neg == 0 or total == 0:
        return 0.5, int(total)
    return float(area / (tot_pos * tot_neg)), int(total)


class GlobalMetrics:
    """Per-worker accumulator for the pslib global metric set
    (metrics.cc): AUC buckets + running error sums, reduced across
    workers at read time."""

    def __init__(self, num_thresholds=4095):
        self.num_thresholds = int(num_thresholds)
        self.reset()

    def reset(self):
        n = self.num_thresholds + 1
        self._pos = np.zeros(n, np.float64)
        self._neg = np.zeros(n, np.float64)
        self._abs_err = 0.0
        self._sq_err = 0.0
        self._prob_sum = 0.0
        self._q_sum = 0.0
        self._pos_sum = 0.0
        self._count = 0.0

    def update(self, preds, labels, q=None):
        """q: optional per-instance quality score (reference metrics.cc
        tracks mean_q separately from predicted ctr); defaults to the
        prediction itself."""
        p = np.asarray(preds, np.float64).reshape(-1)
        y = np.asarray(labels, np.float64).reshape(-1)
        qv = p if q is None else np.asarray(q, np.float64).reshape(-1)
        b = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                    self.num_thresholds)
        np.add.at(self._pos, b[y > 0.5], 1.0)
        np.add.at(self._neg, b[y <= 0.5], 1.0)
        self._abs_err += float(np.abs(p - y).sum())
        self._sq_err += float(((p - y) ** 2).sum())
        self._prob_sum += float(p.sum())
        self._q_sum += float(qv.sum())
        self._pos_sum += float(y.sum())
        self._count += float(len(p))

    def _vector(self):
        return np.concatenate([
            self._pos, self._neg,
            [self._abs_err, self._sq_err, self._prob_sum, self._q_sum,
             self._pos_sum, self._count]])

    def compute(self, all_reduce=None):
        """The global metric dict; `all_reduce(np_array)->np_array` sums
        across workers (identity when None / single worker)."""
        v = self._vector()
        if all_reduce is not None:
            v = np.asarray(all_reduce(v), np.float64)
        n = self.num_thresholds + 1
        pos, neg = v[:n], v[n:2 * n]
        abs_err, sq_err, prob_sum, q_sum, pos_sum, count = v[2 * n:]
        auc, total = _bucket_auc(pos, neg)
        actual_ctr = pos_sum / count if count else 0.0
        predicted_ctr = prob_sum / count if count else 0.0
        # bucket error (metrics.cc bucket_error): impression-weighted
        # |actual - predicted| over score buckets with enough traffic
        min_ins = 1000.0
        err_sum = err_ins = 0.0
        bucket_tot = pos + neg
        with np.errstate(invalid="ignore", divide="ignore"):
            centers = (np.arange(n, dtype=np.float64) + 0.5) / n
            actual_b = np.where(bucket_tot > 0, pos / bucket_tot, 0.0)
            mask = bucket_tot >= min_ins
            err_sum = float((np.abs(actual_b - centers) * bucket_tot)[mask].sum())
            err_ins = float(bucket_tot[mask].sum())
        bucket_error = err_sum / err_ins if err_ins else 0.0
        return {
            "auc": auc,
            "bucket_error": bucket_error,
            "mae": abs_err / count if count else 0.0,
            "rmse": float(np.sqrt(sq_err / count)) if count else 0.0,
            "actual_ctr": actual_ctr,
            "predicted_ctr": predicted_ctr,
            "copc": actual_ctr / predicted_ctr if predicted_ctr else 0.0,
            "mean_q": q_sum / count if count else 0.0,
            "total_ins_num": int(count),
        }


class FleetUtil:
    """reference fleet_util.py:53 — mode 'pslib' surface."""

    def __init__(self, mode="pslib"):
        self.mode = mode

    # ---- rank0 logging -----------------------------------------------------
    def _rank(self):
        from ....distributed.env import get_rank

        return get_rank()

    def rank0_print(self, s):
        if self._rank() == 0:
            print(s, flush=True)

    def rank0_info(self, s):
        if self._rank() == 0:
            _logger.info(s)

    def rank0_error(self, s):
        if self._rank() == 0:
            _logger.error(s)

    # ---- global metrics ----------------------------------------------------
    def _all_reduce(self, arr):
        from ....distributed.env import get_world_size
        from ....distributed.fleet import UtilBase

        if get_world_size() <= 1:
            return np.asarray(arr)  # one rank: local IS global
        # a failed collective must RAISE: silently reporting one
        # worker's buckets as the global metric is the worst outcome
        return UtilBase().all_reduce(np.asarray(arr), mode="sum",
                                     comm_world="worker")

    def set_zero(self, metric):
        metric.reset()

    def get_global_auc(self, metric=None, stat_pos=None, stat_neg=None):
        """Global AUC over all workers. Accepts a paddle.metric.Auc (or
        GlobalMetrics) whose buckets are all-reduced, or raw pos/neg
        bucket arrays; returns (auc, total_ins_num)."""
        if metric is not None:
            pos = getattr(metric, "_stat_pos", None)
            if pos is None:
                pos = metric._pos
            neg = getattr(metric, "_stat_neg", None)
            if neg is None:
                neg = metric._neg
        else:
            pos, neg = stat_pos, stat_neg
        pos = self._all_reduce(np.asarray(pos, np.float64))
        neg = self._all_reduce(np.asarray(neg, np.float64))
        return _bucket_auc(pos, neg)

    def print_global_auc(self, metric=None, print_prefix=""):
        auc, n = self.get_global_auc(metric)
        self.rank0_print(f"{print_prefix} global auc = {auc:.6f} "
                         f"(ins = {n})")
        return auc

    def get_global_metrics(self, metrics: GlobalMetrics):
        return metrics.compute(all_reduce=self._all_reduce)

    def print_global_metrics(self, metrics: GlobalMetrics, print_prefix=""):
        m = self.get_global_metrics(metrics)
        self.rank0_print(
            f"{print_prefix} global metrics: auc={m['auc']:.6f} "
            f"bucket_error={m['bucket_error']:.6f} mae={m['mae']:.6f} "
            f"rmse={m['rmse']:.6f} actual_ctr={m['actual_ctr']:.6f} "
            f"predicted_ctr={m['predicted_ctr']:.6f} copc={m['copc']:.6f} "
            f"ins={m['total_ins_num']}")
        return m

    # ---- day/pass model lifecycle -----------------------------------------
    @staticmethod
    def _model_path(output_path, day, pass_id=None):
        day = str(day)
        if pass_id is None:
            return os.path.join(output_path, day, "base")
        return os.path.join(output_path, day, f"delta-{pass_id}")

    def save_model(self, output_path, day, pass_id):
        from ..parameter_server.pslib import fleet as pslib_fleet

        path = self._model_path(output_path, day, pass_id)
        if self._rank() == 0:  # one writer; peers wait at the barrier
            pslib_fleet.save_persistables(None, path)
        pslib_fleet.barrier_worker()
        self.rank0_print(f"save_model to {path} done")
        return path

    def save_batch_model(self, output_path, day):
        from ..parameter_server.pslib import fleet as pslib_fleet

        path = self._model_path(output_path, day)
        if self._rank() == 0:
            pslib_fleet.save_persistables(None, path)
        pslib_fleet.barrier_worker()
        self.rank0_print(f"save_batch_model to {path} done")
        return path

    def load_model(self, output_path, day, pass_id=None):
        from ..parameter_server.pslib import fleet as pslib_fleet

        path = self._model_path(output_path, day, pass_id)
        pslib_fleet.load_model(path)
        self.rank0_print(f"load_model from {path} done")
        return path

    def write_model_donefile(self, output_path, day, pass_id, xbox_base_key=0,
                             donefile_name="donefile.txt"):
        """Append '<day>\\t<pass>\\t<path>\\t<key>' to the job donefile
        (reference write_model_donefile; the xbox variants are vendor
        sinks and stay out of scope)."""
        if self._rank() != 0:
            return None
        path = self._model_path(output_path, day, pass_id)
        os.makedirs(output_path, exist_ok=True)
        donefile = os.path.join(output_path, donefile_name)
        with open(donefile, "a") as f:
            f.write(f"{day}\t{pass_id}\t{path}\t{xbox_base_key}\n")
        return donefile

    def get_last_save_model(self, output_path,
                            donefile_name="donefile.txt"):
        """(day, pass_id, path) of the newest donefile entry, or
        (-1, -1, None)."""
        donefile = os.path.join(output_path, donefile_name)
        if not os.path.exists(donefile):
            return -1, -1, None
        lines = [ln for ln in open(donefile).read().splitlines() if ln]
        if not lines:
            return -1, -1, None
        day, pass_id, path = lines[-1].split("\t")[:3]
        return int(day), int(pass_id), path

    # ---- online pass intervals --------------------------------------------
    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """Partition a day into passes of `split_per_pass` splits of
        `split_interval` minutes, restricted to the [first, last] hour
        window (reference get_online_pass_interval:1187)."""
        hours = _brace_expand(hours)
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left, right = int(hours[0]), int(hours[-1])

        split_path = []
        start = 0
        for _ in range(splits_per_day):
            h, m = start // 60, start % 60
            start += split_interval
            if h < left or h > right:
                continue
            split_path.append(f"{h:02d}" if is_data_hourly_placed
                              else f"{h:02d}{m:02d}")

        online_pass_interval = []
        start = 0
        for _ in range(pass_per_day):
            chunk = split_path[start:start + split_per_pass]
            if not chunk:
                break
            online_pass_interval.append(chunk)
            start += split_per_pass
        return online_pass_interval

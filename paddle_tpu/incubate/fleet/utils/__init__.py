from .fleet_util import FleetUtil, GlobalMetrics

__all__ = ["FleetUtil", "GlobalMetrics"]

"""paddle.incubate.asp — automatic structured (2:4) sparsity.

Reference: python/paddle/fluid/contrib/sparsity/ (asp.py: decorate /
prune_model / set_excluded_layers, utils.py mask algorithms) targeting
Ampere sparse tensor cores.

TPU note: the MXU has no 2:4 sparse mode, so the hardware speedup doesn't
transfer — but the CAPABILITY (train a network constrained to 2:4 masks,
masks re-applied after every optimizer step) is framework surface the
reference ships, used for sparsity research and for exporting sparse
checkpoints. Masks are computed with the same magnitude-based mask_1d/
mask_2d_greedy algorithms.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["decorate", "prune_model", "set_excluded_layers",
           "reset_excluded_layers", "calculate_density",
           "create_mask", "check_mask_1d"]

_excluded: Dict[int, List[str]] = {}
_masks: Dict[int, np.ndarray] = {}  # id(param) → mask


def set_excluded_layers(param_names, main_program=None):
    _excluded[id(main_program)] = list(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.pop(id(main_program), None)


def calculate_density(x) -> float:
    arr = np.asarray(x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(weight: np.ndarray, func_name="mask_1d", n=2, m=4):
    """2:4 mask: keep the n largest-|w| of every m consecutive inputs
    (reference sparsity/utils.py create_mask)."""
    w = np.asarray(weight)
    if w.ndim < 2 or w.shape[0] % m:
        # pad the reduction dim to a multiple of m
        flat = w.reshape(-1)
        pad = (-flat.size) % m
        padded = np.concatenate([np.abs(flat), np.zeros(pad)])
        groups = padded.reshape(-1, m)
        keep = np.argsort(-groups, axis=1)[:, :n]
        mask = np.zeros_like(groups)
        np.put_along_axis(mask, keep, 1.0, axis=1)
        return mask.reshape(-1)[:flat.size].reshape(w.shape)
    # mask along dim 0 (input dim of [in, out] paddle Linear weights)
    a = np.abs(w).reshape(w.shape[0] // m, m, -1)
    keep = np.argsort(-a, axis=1)[:, :n, :]
    mask = np.zeros_like(a)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    return mask.reshape(w.shape)


def check_mask_1d(mat, n=2, m=4) -> bool:
    arr = np.asarray(mat).reshape(-1)
    pad = (-arr.size) % m
    groups = np.concatenate(
        [arr != 0, np.zeros(pad, bool)]).reshape(-1, m)
    return bool((groups.sum(1) <= n).all())


def _prunable_params(model, excluded):
    out = []
    for name, sub in model.named_sublayers(include_self=True):
        w = getattr(sub, "weight", None)
        if w is None or getattr(w, "stop_gradient", True):
            continue
        if w._value.ndim != 2:
            continue
        pname = getattr(w, "name", "") or name
        if any(e in (pname, name) for e in excluded):
            continue
        out.append(w)
    return out


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply 2:4 masks to every eligible 2-D weight (asp.py prune_model)."""
    import jax.numpy as jnp

    excluded = _excluded.get(id(None), [])
    pruned = {}
    for w in _prunable_params(model, excluded):
        mask = create_mask(np.asarray(w._value), mask_algo, n, m)
        w._value = w._value * jnp.asarray(mask, w._value.dtype)
        if with_mask:
            _masks[id(w)] = mask
        pruned[getattr(w, "name", str(id(w)))] = calculate_density(
            np.asarray(w._value))
    return pruned


class OptimizerWithSparsityGuarantee:
    """asp.decorate product: after every step, re-apply the masks so pruned
    weights stay zero through the update."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        import jax.numpy as jnp

        self._optimizer.step()
        for p in self._optimizer._parameter_list:
            mask = _masks.get(id(p))
            if mask is not None:
                p._value = p._value * jnp.asarray(mask, p._value.dtype)

    def minimize(self, loss, *a, **kw):
        out = self._optimizer.minimize(loss, *a, **kw)
        for p in self._optimizer._parameter_list:
            mask = _masks.get(id(p))
            if mask is not None:
                import jax.numpy as jnp

                p._value = p._value * jnp.asarray(mask, p._value.dtype)
        return out


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)

"""paddle.incubate.autograd — functional differentiation API.

Reference: python/paddle/incubate/autograd/ (primapi.py jvp/vjp,
functional.py Jacobian/Hessian over the prim-op transform system, ~6k LoC
of linearize/transpose rules).

TPU-native: jax IS a functional-differentiation system — jvp/vjp/jacobian/
hessian map 1:1 onto jax transforms over the Tensor-level function, so the
reference's whole prim-op rule engine dissolves into jax.linearize/
jax.vjp/jax.jacfwd/jax.jacrev.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return jnp.asarray(x)


def _wrap(v):
    if isinstance(v, (list, tuple)):
        return type(v)(_wrap(x) for x in v)
    return Tensor(v, _internal=True)


def _lift(func):
    """Tensor-level callable → value-level callable."""

    def fn(*vals):
        args = tuple(Tensor(v, _internal=True) for v in vals)
        for a in args:
            a.stop_gradient = False
        out = func(*args)
        return _unwrap(out)

    return fn


def _astuple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def jvp(func: Callable, xs, v=None):
    """Forward-mode: returns (func(xs), J·v) (reference primapi.py:jvp)."""
    xs_t = _astuple(xs)
    primals = tuple(_unwrap(x) for x in xs_t)
    tangents = (tuple(_unwrap(t) for t in _astuple(v)) if v is not None
                else tuple(jnp.ones_like(p) for p in primals))
    out, jv = jax.jvp(_lift(func), primals, tangents)
    return _wrap(out), _wrap(jv)


def vjp(func: Callable, xs, v=None):
    """Reverse-mode: returns (func(xs), vᵀ·J) (reference primapi.py:vjp)."""
    xs_t = _astuple(xs)
    primals = tuple(_unwrap(x) for x in xs_t)
    out, pullback = jax.vjp(_lift(func), *primals)
    cot = (_unwrap(v) if v is not None
           else jax.tree_util.tree_map(jnp.ones_like, out))
    grads = pullback(cot)
    grads = grads[0] if len(grads) == 1 and not isinstance(
        xs, (list, tuple)) else list(grads)
    return _wrap(out), _wrap(grads)


class Jacobian:
    """Lazy Jacobian matrix (reference functional.py:Jacobian): J[i, j] =
    d out_i / d in_j over flattened in/out; index/slice to materialize."""

    def __init__(self, func: Callable, xs, is_batched: bool = False):
        self._func = func
        self._xs = xs
        self._batched = is_batched
        self._mat = None

    def _materialize(self):
        if self._mat is not None:
            return self._mat
        xs_t = _astuple(self._xs)
        primals = tuple(_unwrap(x) for x in xs_t)
        lifted = _lift(self._func)
        if self._batched:
            # batch dim 0 carried through: J per sample [B, out, in]
            def single(*ps):
                return lifted(*[p[None] for p in ps])[0]

            jac = jax.vmap(lambda *ps: jax.jacrev(single)(*ps))(*primals)
            j = jac if not isinstance(jac, tuple) else jac[0]
            B = j.shape[0]
            out_sz = int(jnp.size(single(*[p[0] for p in primals])))
            self._mat = j.reshape(B, out_sz, -1)
        else:
            jac = jax.jacrev(lifted)(*primals)
            j = jac if not isinstance(jac, tuple) else jac[0]
            out_sz = int(jnp.size(lifted(*primals)))
            self._mat = jnp.reshape(j, (out_sz, -1))
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx], _internal=True)

    @property
    def shape(self):
        return tuple(self._materialize().shape)

    def numpy(self):
        import numpy as np

        return np.asarray(self._materialize())


class Hessian(Jacobian):
    """Lazy Hessian of a scalar-output func (reference functional.py:
    Hessian)."""

    def _materialize(self):
        if self._mat is not None:
            return self._mat
        xs_t = _astuple(self._xs)
        primals = tuple(_unwrap(x) for x in xs_t)
        lifted = _lift(self._func)

        def scalar(*ps):
            return jnp.reshape(lifted(*ps), ())

        if self._batched:
            def single(*ps):
                return jnp.reshape(lifted(*[p[None] for p in ps]), ())

            h = jax.vmap(lambda *ps: jax.hessian(single)(*ps))(*primals)
            h = h if not isinstance(h, tuple) else h[0]
            B = h.shape[0]
            self._mat = h.reshape(B, -1, h.shape[-1]) if h.ndim > 3 else h
            n = int(jnp.size(primals[0][0]))
            self._mat = h.reshape(B, n, n)
        else:
            h = jax.hessian(scalar)(*primals)
            h = h if not isinstance(h, tuple) else h[0]
            n = int(jnp.size(primals[0]))
            self._mat = jnp.reshape(h, (n, n))
        return self._mat


def forward_grad(func: Callable, xs, v=None):
    """Alias of jvp's tangent output (reference primapi.py forward_grad)."""
    return jvp(func, xs, v)[1]


def grad(func: Callable, xs, v=None):
    """Functional reverse grad (reference primapi.py grad)."""
    return vjp(func, xs, v)[1]


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out

"""paddle.incubate — experimental features.

Parity targets: fluid/incubate/checkpoint/auto_checkpoint.py (transparent
epoch-range checkpoint/resume keyed by job id) and incubate.nn helpers.
"""
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401,E402

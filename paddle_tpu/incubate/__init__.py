"""paddle.incubate — experimental features.

Parity targets: fluid/incubate/checkpoint/auto_checkpoint.py (transparent
epoch-range checkpoint/resume keyed by job id) and incubate.nn helpers.
"""
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401,E402
from .optimizer import LookAhead, ModelAverage  # noqa: F401,E402
from ..geometric import (  # noqa: F401,E402
    segment_max, segment_mean, segment_min, segment_sum,
)


def softmax_mask_fuse(x, mask, name=None):
    """Fused masked softmax (reference: incubate softmax_mask_fuse op,
    operators/fused/fused_softmax_mask_op.cu): softmax(x + mask) with the
    additive mask broadcast over heads — one XLA fusion, no materialized
    intermediate in HBM."""
    import jax
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    return call_op(
        lambda v, m: jax.nn.softmax((v + m).astype(jnp.float32), axis=-1)
        .astype(v.dtype),
        x, mask, op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) fused softmax (reference:
    fused_softmax_mask_upper_triangle_op.cu): rows attend only to earlier
    columns; implemented as one fused where+softmax."""
    import jax
    import jax.numpy as jnp

    from ..framework.autograd import call_op

    def fn(v):
        q = v.shape[-2]
        k = v.shape[-1]
        causal = jnp.tril(jnp.ones((q, k), bool), k=k - q)
        z = jnp.where(causal, v.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(v.dtype)

    return call_op(fn, x, op_name="softmax_mask_fuse_upper_triangle")


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       return_eids=False, name=None):
    """K-hop neighbor sampling over a CSR graph (reference:
    incubate.graph_khop_sampler / graph_khop_sampler_op.cc). Host-side (the
    reference samples on CPU too): expands `input_nodes` layer by layer,
    sampling up to sample_sizes[i] neighbors per node at hop i.

    Returns (edge_src, edge_dst, sample_index, reindex_nodes) — edges in
    reindexed ids, the unique node list, and the reindexed seed ids —
    matching the reference's contract (eids appended when return_eids).
    """
    import numpy as np

    from ..framework.tensor import Tensor

    def _np(v):
        return np.asarray(v.numpy() if isinstance(v, Tensor) else v)

    rows = _np(row).reshape(-1)
    ptr = _np(colptr).reshape(-1)
    seeds = _np(input_nodes).reshape(-1).astype(np.int64)

    srcs, dsts, eids = [], [], []
    frontier = seeds
    for size in sample_sizes:
        nxt = []
        for u in frontier:
            lo, hi = int(ptr[u]), int(ptr[u + 1])
            deg = hi - lo
            if deg == 0:
                continue
            take = np.arange(lo, hi)
            if deg > int(size):
                take = np.random.choice(take, int(size), replace=False)
            for e in take:
                srcs.append(int(rows[e]))
                dsts.append(int(u))
                eids.append(int(e))
            nxt.extend(int(rows[e]) for e in take)
        frontier = np.asarray(sorted(set(nxt)), np.int64)

    uniq = list(dict.fromkeys(
        list(seeds) + srcs + dsts))  # seeds first, stable order
    remap = {n: i for i, n in enumerate(uniq)}
    from ..framework.tensor import to_tensor

    out = (
        to_tensor(np.asarray([remap[s] for s in srcs], np.int64)),
        to_tensor(np.asarray([remap[d] for d in dsts], np.int64)),
        to_tensor(np.asarray(uniq, np.int64)),
        to_tensor(np.asarray([remap[s] for s in seeds], np.int64)),
    )
    if return_eids:
        out = out + (to_tensor(np.asarray(eids, np.int64)),)
    return out

"""AMP (reference: python/paddle/amp/{auto_cast.py,grad_scaler.py} + C++
imperative/amp_auto_cast.cc allow/block lists, operators/amp/*).

TPU-native: bf16 is the native mixed-precision dtype (MXU computes bf16 at
full rate); loss scaling is unnecessary for bf16 (same exponent range as fp32)
but the GradScaler API is preserved — with real scaling + finite checks when
fp16 is explicitly requested.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.autograd import no_grad
from ..framework.tensor import Tensor

_tls = threading.local()

# mirror of the reference's O1 white/black lists (imperative/amp_auto_cast.cc)
WHITE_LIST = {"matmul", "linear", "conv1d", "conv2d", "conv3d", "bmm", "mm", "einsum",
              "addmm", "mv"}
BLACK_LIST = {"exp", "log", "log2", "log10", "mean", "sum", "softmax",
              "log_softmax", "cross_entropy", "layer_norm", "batch_norm", "norm",
              "cumsum", "logsumexp", "softmax_with_cross_entropy"}


def amp_state():
    return getattr(_tls, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16"):
    """paddle.amp.auto_cast context manager."""
    prev = amp_state()
    if enable:
        white = set(WHITE_LIST)
        black = set(BLACK_LIST)
        if custom_white_list:
            white |= set(custom_white_list)
            black -= set(custom_white_list)
        if custom_black_list:
            black |= set(custom_black_list)
            white -= set(custom_black_list)
        _tls.amp = {
            "level": level,
            "dtype": dtype_mod.convert_dtype(dtype),
            "white": white,
            "black": black,
        }
    else:
        _tls.amp = None
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast


def amp_cast_inputs(op_name, vals):
    """Called from the dispatch layer: cast op inputs per the active policy."""
    st = amp_state()
    if st is None:
        return vals
    dt = st["dtype"]
    if st["level"] == "O2":
        if op_name in st["black"]:
            return [
                v.astype(jnp.float32) if _is_low(v) else v for v in vals
            ]
        return [_cast_float(v, dt) for v in vals]
    # O1
    if op_name in st["white"]:
        return [_cast_float(v, dt) for v in vals]
    if op_name in st["black"]:
        return [v.astype(jnp.float32) if _is_low(v) else v for v in vals]
    return vals


def _is_low(v):
    return v.dtype in (jnp.bfloat16, jnp.float16)


def _cast_float(v, dt):
    if jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != dt:
        return v.astype(dt)
    return v


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2 casts model params to the AMP dtype.

    Optimizer fp32 master math is built in (optimizer slots are fp32), which is
    the reference's multi_precision behavior."""
    from ..nn import Layer

    single_model = isinstance(models, Layer)
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if p.dtype == np.float32:
                    p._value = p._value.astype(dtype_mod.convert_dtype(dtype))
    out_models = model_list[0] if single_model else model_list
    if optimizers is None:
        return out_models
    return out_models, optimizers


class GradScaler:
    """paddle.amp.GradScaler (amp/grad_scaler.py:26).

    With bf16 the scale stays at init and nothing overflows; with fp16 the full
    dynamic-loss-scaling protocol runs (check_finite → skip + shrink scale, or
    grow after N good steps) — matching operators/amp/{check_finite_and_unscale,
    update_loss_scaling}."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        # True while the most recent step() skipped the update because the
        # scaler found inf/nan grads — read by the robustness NaN guard,
        # which must NOT count scaler-skipped steps toward its circuit
        # breaker (routine fp16 overflow handling, not divergence)
        self.last_step_skipped = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        """Idempotent per step (reference guards with OptimizerState.UNSCALED)."""
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        found = False
        with no_grad():
            for p in optimizer._parameter_list:
                if p.grad is not None:
                    g = p.grad._value.astype(jnp.float32) * inv
                    found = found or bool(~jnp.isfinite(g).all())
                    p.grad._value = g
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            self.last_step_skipped = False
            return
        self.unscale_(optimizer)  # no-op if the user already unscaled
        self.last_step_skipped = self._found_inf
        if self._found_inf:
            self._on_bad_step()
        else:
            optimizer.step()
            self._on_good_step()
        self._found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, *args, **kwargs):
        """reference: grad_scaler.py minimize — the USER calls
        scaled.backward() first; minimize only unscales + steps."""
        self.step(optimizer)
        self.update()

    def update(self):
        pass  # state updated in step()

    def _on_good_step(self):
        if not self._dynamic:
            return
        self._good_steps += 1
        self._bad_steps = 0
        if self._good_steps >= self._incr_every:
            self._scale *= self._incr_ratio
            self._good_steps = 0

    def _on_bad_step(self):
        if not self._dynamic:
            return
        self._bad_steps += 1
        self._good_steps = 0
        if self._bad_steps >= self._decr_every:
            from ..framework.flags import flag

            self._scale = max(self._scale * self._decr_ratio,
                              float(flag("FLAGS_min_loss_scaling", 1.0)))
            self._bad_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)

    set_state_dict = load_state_dict

from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
from .model_summary import flops, summary  # noqa: F401
